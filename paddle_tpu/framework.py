"""Framework-level helpers: save/load, dygraph/static mode flags.

Parity: python/paddle/framework/ (save/load from python/paddle/framework/io.py,
in_dygraph_mode from fluid/framework.py).
"""
import io as _io
import os
import pickle

import numpy as np

from .core.tensor import Tensor, Parameter

_static_mode = [False]


def in_dynamic_mode():
    return not _static_mode[0]


def in_dygraph_mode():
    return not _static_mode[0]


def in_static_mode():
    return _static_mode[0]


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj.numpy()),
                              is_param=isinstance(obj, Parameter),
                              name=obj.name)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def _from_saveable(obj, return_numpy=False):
    import jax.numpy as jnp
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        t = Parameter(jnp.asarray(obj.array), name=obj.name) if obj.is_param \
            else Tensor(jnp.asarray(obj.array), name=obj.name)
        return t
    if isinstance(obj, dict):
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_from_saveable(v, return_numpy) for v in obj)
    return obj


class _TensorPayload:
    __slots__ = ('array', 'is_param', 'name')

    def __init__(self, array, is_param=False, name=None):
        self.array = array
        self.is_param = is_param
        self.name = name


def save(obj, path, protocol=4, **configs):
    """paddle.save — pickles nested state (Tensors -> numpy payloads).

    Writes go through the resilience atomic commit (same-dir temp + fsync +
    os.replace): a crash mid-save leaves the previous file intact instead of
    a torn pickle that load() would die on.
    """
    from .resilience.atomic_io import atomic_pickle_dump
    payload = _to_saveable(obj)
    atomic_pickle_dump(payload, path, protocol=protocol)


def load(path, **configs):
    """paddle.load — counterpart of save(); also reads .npz archives."""
    return_numpy = configs.get('return_numpy', False)
    if path.endswith('.npz'):
        data = np.load(path, allow_pickle=True)
        return {k: data[k] for k in data.files}
    try:
        with open(path, 'rb') as f:
            payload = pickle.load(f)
    except (EOFError, pickle.UnpicklingError) as e:
        raise RuntimeError(
            "paddle.load: %r is truncated or corrupt (%s). Files written by "
            "this build commit atomically, so this usually means an external "
            "copy was torn; for rotating checkpoints with automatic fallback "
            "to the last good one, use resilience.CheckpointManager."
            % (path, e)) from e
    return _from_saveable(payload, return_numpy)


def set_grad_enabled(mode):
    from .core import autograd
    return autograd.set_grad_enabled(mode)


# -- 2.0-beta paddle.framework namespace tail (reference python/paddle/
# framework/__init__.py re-exports; one implementation each) ---------------
from .core.place import (CPUPlace, CUDAPlace,  # noqa: E402,F401
                         CUDAPinnedPlace)
from .core.autograd import no_grad, grad  # noqa: E402,F401


def __getattr__(name):
    _lazy = {
        'CosineDecay', 'ExponentialDecay', 'InverseTimeDecay',
        'NaturalExpDecay', 'NoamDecay', 'PiecewiseDecay', 'PolynomialDecay',
        'SaveLoadConfig', 'manual_seed', 'get_default_dtype',
        'set_default_dtype', 'get_cuda_rng_state', 'set_cuda_rng_state',
        'ParamAttr', 'create_parameter', 'create_global_var',
    }
    if name in _lazy:
        # top-level paddle_tpu owns these; lazy because this module loads
        # before the package finishes initializing
        import paddle_tpu
        return getattr(paddle_tpu, name)
    if name == 'DataParallel':
        from .distributed import DataParallel
        return DataParallel
    if name == 'LayerList':
        from .nn import LayerList
        return LayerList
    if name == 'Variable':
        from .static.graph import Variable
        return Variable
    if name == 'to_variable':
        from .fluid.dygraph import to_variable
        return to_variable
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
