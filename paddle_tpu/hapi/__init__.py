"""High-level API. Parity: python/paddle/hapi/__init__.py."""
from .model import Model
from . import callbacks
from .model_summary import summary, flops
from .callbacks import Callback, ModelCheckpoint, ProgBarLogger  # noqa: F401
from .progressbar import ProgressBar  # noqa: F401
