"""Training callbacks. Parity: python/paddle/hapi/callbacks.py."""
import json
import os

import numpy as np

from .progressbar import ProgressBar

__all__ = ['Callback', 'ProgBarLogger', 'ModelCheckpoint', 'LRScheduler',
           'EarlyStopping', 'VisualDL', 'CallbackList', 'CheckpointSaver',
           'TelemetryCallback']


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_batch_begin(self, step, logs=None):
        pass

    def on_predict_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith('on_'):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)
            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = self.params.get('steps')
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")
        self.bar = ProgressBar(num=self.steps, verbose=self.verbose)

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose and step % self.log_freq == 0:
            vals = [(k, v) for k, v in logs.items()
                    if isinstance(v, (int, float, np.floating))]
            self.bar.update(step + 1, vals)

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        if self.verbose:
            vals = [(k, v) for k, v in logs.items()
                    if isinstance(v, (int, float, np.floating))]
            self.bar.update(self.steps or 0, vals)

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.verbose:
            info = ' - '.join(f"{k}: {v}" for k, v in logs.items())
            print(f"Eval: {info}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, 'final'))


class CheckpointSaver(Callback):
    """Preemption-safe training checkpoints (resilience.CheckpointManager).

    Saves the FULL resumable state — network params, optimizer accumulators,
    RNG streams (paddle generator + numpy), AMP loss scale, NaN-guard
    counters, epoch/step position — as CRC-stamped rotating checkpoints:

    - every ``save_freq`` epochs at the epoch boundary;
    - immediately at the next batch boundary after SIGTERM (fleet
      preemption), then stops training cleanly.

    Resume with ``Model.fit(..., resume_from=<same dir>)``: training
    continues bitwise-identically to a never-interrupted run (the epoch-start
    RNG snapshot lets a mid-epoch resume replay the epoch's shuffle, skip the
    completed steps, then restore the exact mid-epoch RNG state).

    ``async_save=True`` commits epoch-boundary checkpoints on a background
    thread (``CheckpointManager.save(async_=True)``): the training thread's
    stall is the snapshot enqueue only. The PREEMPTION checkpoint is always
    synchronous — and it first *fences* any in-flight async save (finish,
    or cleanly abandon after ``preempt_fence_s`` seconds) so the two can
    never interleave half-written artifacts inside the grace window.
    """

    def __init__(self, save_dir, save_freq=1, max_keep=3,
                 save_on_preempt=True, async_save=False,
                 preempt_fence_s=5.0):
        super().__init__()
        self.save_dir = save_dir
        self.save_freq = save_freq
        self.max_keep = max_keep
        self.save_on_preempt = save_on_preempt
        self.async_save = bool(async_save)
        self.preempt_fence_s = float(preempt_fence_s)
        self._mgr = None
        self._guard = None
        self._epoch = 0
        self._preempt_saved = False

    def manager(self):
        if self._mgr is None:
            from ..resilience import CheckpointManager
            self._mgr = CheckpointManager(self.save_dir,
                                          max_keep=self.max_keep)
        return self._mgr

    def on_train_begin(self, logs=None):
        self.manager()
        self._preempt_saved = False
        if self.save_on_preempt and self._guard is None:
            from ..resilience import PreemptionGuard
            self._guard = PreemptionGuard().install()

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_train_batch_end(self, step, logs=None):
        if self._guard is not None and self._guard.preempted and \
                not self._preempt_saved:
            # an async epoch-boundary save may still be committing: fence
            # it (finish, or abandon its uncommitted artifacts) BEFORE the
            # preemption checkpoint starts — two concurrent writers inside
            # the grace window was the race the sync-only path never had.
            # A prior background save's stored failure must not abort this
            # final save: it is the last chance to persist progress.
            try:
                self.manager().fence(timeout=self.preempt_fence_s,
                                     abandon=True)
            except Exception:
                pass
            # step+1 batches of this epoch are complete; resume skips them
            self._save(epoch=self._epoch, step_in_epoch=step + 1,
                       async_ok=False)
            self._preempt_saved = True
            self.model.stop_training = True

    def on_epoch_end(self, epoch, logs=None):
        if self._preempt_saved:
            return   # the preemption checkpoint already holds this position
        if (epoch + 1) % self.save_freq == 0:
            self._save(epoch=epoch + 1, step_in_epoch=0)

    def on_train_end(self, logs=None):
        if self._guard is not None:
            self._guard.uninstall()
            self._guard = None
        if self._mgr is not None:
            # the final async save must land before the process can exit
            self._mgr.fence()

    @property
    def preempted(self):
        return self._preempt_saved

    def _save(self, epoch, step_in_epoch, async_ok=True):
        from ..resilience import capture_rng
        model = self.model
        model._sync_jit_state()
        state = {
            'model': model.network.state_dict(),
            'rng': capture_rng(),
            'epoch_start_rng': getattr(model, '_epoch_start_rng', None),
        }
        if model._optimizer is not None:
            state['opt'] = model._optimizer.state_dict()
        scaler = getattr(model, '_scaler', None)
        if scaler is not None:
            state['scaler'] = scaler.state_dict()
        guard = getattr(model, '_nan_guard', None)
        if guard is not None:
            state['nan_guard'] = guard.state_dict()
        self.manager().save(state, meta={'epoch': int(epoch),
                                         'step_in_epoch': int(step_in_epoch)},
                            async_=self.async_save and async_ok)


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, '_optimizer', None)
        from ..optimizer.lr import LRScheduler as Sched
        if opt and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor='loss', mode='auto', patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        self.stopped_epoch = 0
        if mode == 'min' or (mode == 'auto' and 'loss' in monitor):
            self.monitor_op = np.less
            self.min_delta *= -1
        else:
            self.monitor_op = np.greater
        self.best = None
        self.wait = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        current = logs.get(self.monitor)
        if current is None:
            return
        if isinstance(current, (list, tuple)):
            current = current[0]
        if self.best is None or self.monitor_op(current - self.min_delta,
                                                self.best):
            self.best = current
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping: best {self.monitor}={self.best}")


class VisualDL(Callback):
    """Scalar logger writing JSONL (VisualDL itself not bundled)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        self._f = None
        self._step = 0

    def on_train_begin(self, logs=None):
        self._f = open(os.path.join(self.log_dir, 'scalars.jsonl'), 'a')

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        from ..observability import wall_ts
        rec = {'step': self._step, 'ts': wall_ts()}
        for k, v in logs.items():
            if isinstance(v, (int, float, np.floating)):
                rec[k] = float(v)
        self._f.write(json.dumps(rec) + '\n')
        self._step += 1

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()


def __getattr__(name):
    # TelemetryCallback lives in observability (which imports Callback from
    # this module); resolve lazily to keep the import graph acyclic.
    if name == 'TelemetryCallback':
        from ..observability.callback import TelemetryCallback
        return TelemetryCallback
    raise AttributeError(name)
