"""paddle.Model: high-level train/eval/predict API.

Parity: python/paddle/hapi/model.py. TPU-first: the inner train step runs
through the eager tape (jit-compiled train-step variant available via
prepare(jit=True) using nn.functional_call + optimizer.functional_update —
one XLA computation per step).
"""
import os

import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..core import autograd
from ..io import DataLoader
from ..metric import Metric
from .callbacks import CallbackList, ProgBarLogger


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._jit_step = None
        self._jit_state = None
        self._use_jit = False
        self._sharding_cfg = None
        self._scaler = None
        self._nan_guard = None
        self._epoch_start_rng = None
        self._fit_log_freq = 10
        self._steps_since_engine_sync = 0

    # -- setup --------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, jit=False,
                amp_configs=None, nan_guard=None, strategy=None):
        self._optimizer = optimizer
        self._loss = loss
        self._set_strategy(strategy)
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, Metric):
            self._metrics = [metrics]
        else:
            self._metrics = list(metrics)
        from ..amp import GradScaler
        self._scaler = None
        if isinstance(amp_configs, GradScaler):
            self._scaler = amp_configs
        elif isinstance(amp_configs, dict) and \
                isinstance(amp_configs.get('scaler'), GradScaler):
            self._scaler = amp_configs['scaler']
        self._nan_guard = None
        if nan_guard:
            from ..resilience import NanGuard
            self._nan_guard = nan_guard if isinstance(nan_guard, NanGuard) \
                else NanGuard()
            if self._scaler is not None:
                self._nan_guard.attach_scaler(self._scaler)
        self._use_jit = jit or self._sharding_cfg is not None
        if self._use_jit:
            self._build_jit_step()
        return self

    def _set_strategy(self, strategy):
        """Resolve a sharding strategy (fleet ``DistributedStrategy``,
        ``distributed.ShardingConfig``, or None). When none is given but
        the optimizer is a ``fleet.distributed_optimizer`` wrapper that
        carries a resolved config, adopt that — the fleet knobs and the
        hapi ``strategy=`` argument must mean the same thing."""
        from ..distributed.strategy import resolve_sharding
        cfg = resolve_sharding(strategy)
        if cfg is None and strategy is None:
            cfg = getattr(self._optimizer, 'sharding_config', None)
        self._sharding_cfg = cfg

    def _build_jit_step(self):
        """Fully-jitted train step via the unified engine builder: ONE XLA
        program with buffer donation (where the backend honors it), the
        in-graph NaN guard, AMP loss scaling, and (with a strategy) the
        FSDP/tensor-parallel sharding plan folded in (docs/PERF.md)."""
        from ..engine import build_train_step
        scaler = self._scaler if (self._scaler is not None and
                                  self._scaler.is_enable()) else None
        self._jit_step_fn = build_train_step(
            net=self.network, loss=self._loss, optimizer=self._optimizer,
            scaler=scaler, nan_guard=self._nan_guard is not None,
            sharding=self._sharding_cfg)
        self._jit_state = None
        self._steps_since_engine_sync = 0

    # -- steps --------------------------------------------------------------
    def train_batch(self, inputs, labels=None):
        self.network.train()
        inputs = self._to_list(inputs)
        labels = self._to_list(labels)
        if self._use_jit:
            return self._jit_train_batch(inputs, labels)
        outs = self.network(*[self._tensor(i) for i in inputs])
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        losses = self._loss(*outs, *[self._tensor(l) for l in labels])
        losses_list = losses if isinstance(losses, (list, tuple)) else [losses]
        total = losses_list[0]
        for l in losses_list[1:]:
            total = total + l
        if self._nan_guard is not None and self._nan_guard.check(total):
            # poisoned loss: no backward, no update — also decays the AMP
            # loss scale through the attached GradScaler
            self._optimizer.clear_grad()
            metrics = self._update_metrics(outs, labels)
            return [float(l.numpy()) for l in losses_list], metrics
        if self._scaler is not None and self._scaler.is_enable():
            self._scaler.scale(total).backward()
            self._scaler.step(self._optimizer)   # skips the step on inf grads
            self._optimizer.clear_grad()
        else:
            total.backward()
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outs, labels)
        return [float(l.numpy()) for l in losses_list], metrics

    def _jit_train_batch(self, inputs, labels, lazy=False):
        from ..engine.loop import adopt_optimizer_state
        from ..nn.layer_base import param_values, buffer_values
        from ..core import rng as _rng
        if self._jit_state is None:
            pv = param_values(self.network)
            # adopt restored eager accumulators (optimizer.set_state_dict on
            # resume) instead of fresh zeros: jit resume must continue
            # Adam/Momentum moments exactly like the eager path does
            self._jit_state = self._jit_step_fn.init_state(
                pv, buffer_values(self.network),
                opt_state=adopt_optimizer_state(self.network,
                                                self._optimizer, pv),
                nan_guard=self._nan_guard, scaler=self._scaler)
            self._steps_since_engine_sync = 0
        bx = tuple(self._tensor(i)._value for i in inputs)
        by = tuple(self._tensor(l)._value for l in labels)
        key = _rng.next_key()
        # a poisoned step is skipped IN-GRAPH (lax.cond selects the pre-step
        # state), so no host-side rollback snapshot exists to clash with
        # buffer donation; host-side guard/scaler bookkeeping reconciles at
        # the log cadence (or immediately for direct train_batch calls)
        self._jit_state, out = self._jit_step_fn(self._jit_state, (bx, by),
                                                 key)
        if self._jit_step_fn.guard_enabled or \
                self._jit_step_fn.scaler is not None:
            self._steps_since_engine_sync += 1
            if not lazy or self._steps_since_engine_sync >= \
                    self._engine_sync_every():
                self._engine_sync()
        outs = [Tensor(v) for v in out.outputs]
        metrics = self._update_metrics(outs, labels)
        loss = out.loss if lazy else float(out.loss)
        return [loss], metrics

    def _engine_sync_every(self):
        """Guard/scaler host-reconcile cadence inside fit(): the log
        cadence, tightened so a diverging run can never overshoot the
        NaN guard's consecutive-skip limit by more than one cadence."""
        every = self._fit_log_freq
        if self._nan_guard is not None:
            every = min(every, self._nan_guard.max_consecutive_skips)
        return max(int(every), 1)

    def _engine_sync(self, raise_on_limit=True):
        """Reconcile in-graph guard/scaler counters with the host objects
        (may raise NanStepError at the consecutive-skip limit)."""
        self._steps_since_engine_sync = 0
        if self._jit_state is None:
            return
        self._jit_step_fn.sync(self._jit_state, nan_guard=self._nan_guard,
                               scaler=self._scaler,
                               raise_on_limit=raise_on_limit)

    def _fit_train_batch(self, inputs, labels):
        """train_batch with the fit-loop contract: on the jit path the
        returned loss is an engine ``DeviceLoss`` (materialized by the
        loop at log cadence only) and guard/scaler host bookkeeping
        reconciles on the same cadence instead of every step."""
        if not self._use_jit:
            return self.train_batch(inputs, labels)
        self.network.train()
        return self._jit_train_batch(self._to_list(inputs),
                                     self._to_list(labels), lazy=True)

    def _sync_jit_state(self):
        if self._jit_state is not None:
            # mirror the functional state (params, buffers, optimizer
            # moments) back into the eager world so state_dict()/
            # checkpointing sees the live values, and reconcile the
            # in-graph guard/scaler counters (never raising from here —
            # this also runs in fit()'s finally block)
            from ..engine.loop import write_back_state
            write_back_state(self.network, self._optimizer, self._jit_state)
            step = getattr(self, '_jit_step_fn', None)
            if step is not None and getattr(step, 'sync', None) is not None:
                step.sync(self._jit_state, nan_guard=self._nan_guard,
                          scaler=self._scaler, raise_on_limit=False)

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        self._sync_jit_state()
        inputs = self._to_list(inputs)
        labels = self._to_list(labels)
        with autograd.no_grad():
            outs = self.network(*[self._tensor(i) for i in inputs])
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        losses = []
        if self._loss is not None and labels:
            l = self._loss(*outs, *[self._tensor(x) for x in labels])
            losses = [float(x.numpy()) for x in
                      (l if isinstance(l, (list, tuple)) else [l])]
        metrics = self._update_metrics(outs, labels)
        return losses, metrics

    def predict_batch(self, inputs):
        self.network.eval()
        self._sync_jit_state()
        inputs = self._to_list(inputs)
        with autograd.no_grad():
            outs = self.network(*[self._tensor(i) for i in inputs])
        outs = outs if isinstance(outs, (list, tuple)) else [outs]
        return [o.numpy() for o in outs]

    # -- loops --------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            resume_from=None, strategy=None):
        """Train for ``epochs`` epochs.

        ``strategy``: a ``distributed.ShardingConfig`` or a fleet
        ``DistributedStrategy`` with ``sharding``/``tensor_parallel`` set —
        the train step compiles with params/optimizer state sharded over
        the mesh (sharded training runs through the compiled path, so this
        implies ``jit=True``; docs/PERF.md, "Sharded training").

        ``resume_from``: a directory previously written by a
        :class:`~paddle_tpu.hapi.callbacks.CheckpointSaver` callback (or a
        ``resilience.CheckpointManager``). The newest non-corrupt checkpoint
        restores params, optimizer accumulators, AMP loss scale, NaN-guard
        counters, and both RNG streams, then training continues from the
        recorded epoch/step — bitwise-identical to a run that was never
        interrupted. A SIGTERM during training (with a CheckpointSaver
        active) checkpoints at the next batch boundary and stops cleanly.
        """
        if strategy is not None:
            prev_cfg = self._sharding_cfg
            self._set_strategy(strategy)
            changed = self._sharding_cfg is not prev_cfg
            if self._sharding_cfg is not None and \
                    (changed or not self._use_jit):
                # sharding lives in the compiled step. Write any prior
                # jitted progress back into the eager net first — the
                # rebuild drops _jit_state, and the new state re-inits
                # from the network
                self._sync_jit_state()
                self._use_jit = True
                self._build_jit_step()
            elif changed and prev_cfg is not None and self._use_jit:
                # an explicit knobs-off strategy turns sharding OFF: the
                # old sharded step may not silently keep running under a
                # config that now claims "unsharded"
                self._sync_jit_state()
                self._build_jit_step()
            # a knobs-off strategy on a never-sharded model (or the same
            # config again) changes nothing — in particular it must not
            # flip the model onto the jit path or reset accumulated state
        train_loader = self._to_loader(train_data, batch_size, shuffle,
                                       drop_last, num_workers)
        eval_loader = self._to_loader(eval_data, batch_size, False, False,
                                      num_workers) if eval_data is not None \
            else None
        user_cbks = list(callbacks or [])
        from .. import observability as _obs
        if _obs.enabled() and not any(
                isinstance(c, _obs.TelemetryCallback) for c in user_cbks):
            # PADDLE_TPU_TELEMETRY=1: every fit() emits step events + spans
            # without code changes (docs/OBSERVABILITY.md)
            user_cbks.insert(0, _obs.TelemetryCallback())
        cbks = CallbackList([ProgBarLogger(log_freq, verbose)] + user_cbks)
        cbks.set_model(self)
        # jit path: the loss stays on-device between log points; this is
        # the materialization (and guard/scaler reconcile) cadence
        self._fit_log_freq = max(int(log_freq), 1)
        self._steps_since_engine_sync = 0
        steps = None
        try:
            steps = len(train_loader)
        except TypeError:
            pass
        cbks.set_params({'epochs': epochs, 'steps': steps, 'verbose': verbose})
        start_epoch, skip_steps, resume_rng = 0, 0, None
        if resume_from is not None:
            start_epoch, skip_steps, resume_rng = \
                self._restore_checkpoint(resume_from)
        cbks.on_train_begin()
        self.stop_training = False
        from ..resilience.checkpoint import capture_rng, restore_rng
        try:
            self._fit_loop(train_loader, eval_loader, cbks, epochs,
                           start_epoch, skip_steps, resume_rng, eval_freq,
                           save_dir, save_freq, capture_rng, restore_rng)
        finally:
            # always: on_train_end uninstalls CheckpointSaver's SIGTERM
            # handler — leaking it past an exception (e.g. NanStepError)
            # would leave the process ignoring the scheduler's SIGTERM
            self._sync_jit_state()
            cbks.on_train_end()
            # a run that silently skipped poisoned samples is not the same
            # run as a clean one: surface the DataLoader quarantine report
            # (docs/RESILIENCE.md) instead of leaving it in a loss curve
            report = getattr(train_loader, 'quarantine_report', None)
            quarantined = report() if callable(report) else []
            if quarantined:
                import warnings
                warnings.warn(
                    f"DataLoader quarantined {len(quarantined)} poisoned "
                    f"sample(s) during fit(): {quarantined}",
                    RuntimeWarning, stacklevel=2)

    def _fit_loop(self, train_loader, eval_loader, cbks, epochs, start_epoch,
                  skip_steps, resume_rng, eval_freq, save_dir, save_freq,
                  capture_rng, restore_rng):
        for epoch in range(start_epoch, epochs):
            resuming = resume_rng is not None and epoch == start_epoch
            if resuming and skip_steps == 0:
                # epoch-boundary resume: continue the RNG streams exactly
                # where the checkpoint left them (before this epoch's
                # shuffle draws)
                restore_rng(resume_rng['save_point'])
            elif resuming:
                # mid-epoch resume: rewind to the epoch-start snapshot so
                # iterating the loader below replays the SAME shuffle the
                # interrupted epoch used
                restore_rng(resume_rng['epoch_start'])
            # epoch-start snapshot (taken BEFORE the loader draws shuffle
            # randomness): lets a mid-epoch preemption checkpoint replay
            # this epoch's batch order on resume
            self._epoch_start_rng = capture_rng()
            cbks.on_epoch_begin(epoch)
            logs = {}
            mid_restore_pending = resuming and skip_steps > 0
            for step, batch in enumerate(train_loader):
                if resuming and step < skip_steps:
                    continue   # already trained before the preemption
                if mid_restore_pending:
                    # shuffle replayed, completed steps skipped: now adopt
                    # the exact RNG state of the preemption point
                    restore_rng(resume_rng['save_point'])
                    mid_restore_pending = False
                cbks.on_train_batch_begin(step)
                ins, lbs = self._split_batch(batch)
                losses, metrics = self._fit_train_batch(ins, lbs)
                loss0 = losses[0]
                if step % self._fit_log_freq == 0 and \
                        not isinstance(loss0, float):
                    # log-cadence host sync: the only point a steady-state
                    # jit step's loss crosses to the host
                    loss0 = float(loss0)
                logs = {'loss': loss0}
                for m, res in zip(self._metrics, metrics):
                    names = m.name() if isinstance(m.name(), list) else \
                        [m.name()]
                    vals = res if isinstance(res, (list, tuple)) else [res]
                    for n, v in zip(names, vals):
                        logs[n] = float(v)
                cbks.on_train_batch_end(step, logs)
                if self.stop_training:
                    break
            if mid_restore_pending:
                # preemption landed on the epoch's final batch: nothing to
                # retrain here, but the RNG streams must still continue from
                # the preemption point, not the replayed-shuffle state
                restore_rng(resume_rng['save_point'])
            if self.stop_training:
                # preempted mid-epoch: the CheckpointSaver already committed
                # this position; skip epoch-end bookkeeping that would
                # otherwise record the partial epoch as complete
                break
            if 'loss' in logs and not isinstance(logs['loss'], float):
                logs['loss'] = float(logs['loss'])   # epoch-boundary sync
            cbks.on_epoch_end(epoch, logs)
            for m in self._metrics:
                m.reset()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          _from_fit=True)
                cbks.on_eval_end(eval_logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
            if self.stop_training:
                break

    def _restore_checkpoint(self, resume_from):
        """Restore the newest non-corrupt CheckpointSaver checkpoint.

        Returns ``(start_epoch, skip_steps, rng_snapshots)``; with no
        loadable checkpoint, training starts fresh (warning) — the standard
        preemption-loop contract where the first run of a job has no
        checkpoint yet.
        """
        import warnings
        from ..resilience import CheckpointManager
        mgr = resume_from if isinstance(resume_from, CheckpointManager) \
            else CheckpointManager(resume_from)
        loaded = mgr.load()
        if loaded is None:
            warnings.warn(
                "Model.fit(resume_from=%r): no loadable checkpoint found — "
                "starting from scratch" % (mgr.path,))
            return 0, 0, None
        state, meta = loaded
        if 'model' not in state and 'params' in state:
            # an engine-layout (sharded, format-2) checkpoint written by
            # engine.fit / an elastic worker — possibly on a DIFFERENT
            # mesh shape: the manager already reassembled the global
            # arrays, so adopting them here IS the resharding restore
            # (this model's own strategy re-shards at the next jit
            # init_state). Functional opt slots map back through the
            # same helper the jit loop uses.
            from ..engine.loop import write_back_state
            write_back_state(self.network, self._optimizer, state)
            if self._use_jit:
                self._jit_state = None
            if self._scaler is not None and \
                    isinstance(state.get('scaler'), dict) and \
                    'scale' in state['scaler']:
                self._scaler._scale = float(
                    np.asarray(state['scaler']['scale']))
            start = int(meta.get('epoch', 0))
            # a mid-epoch engine checkpoint records how many dispatches of
            # the epoch are already trained — skip them instead of double-
            # stepping the optimizer on consumed data. Engine checkpoints
            # carry ONE RNG snapshot (the save point): exact for epoch-
            # boundary resumes and for deterministic (unshuffled) loaders;
            # a shuffled mid-epoch hapi resume cannot replay the epoch's
            # shuffle from it (engine.fit never shuffles).
            # one engine dispatch consumes k (microbatch) hapi-sized
            # batches — skip batches, not dispatches
            skip = int(meta.get('dispatch_in_epoch', 0)) * \
                int(meta.get('microbatch', 1))
            rng = None
            extra = mgr.load_extra(
                step=int(meta['dispatches'])
                if meta.get('dispatches') is not None else None)
            if extra is not None and extra.get('rng') is not None:
                rng = {'save_point': extra['rng'],
                       'epoch_start': extra['rng']}
            elif skip:
                # no RNG payload but a position to honor: skip with the
                # streams left as-is rather than retrain consumed batches
                rng = {'save_point': None, 'epoch_start': None}
            return start, skip, rng
        self.network.set_state_dict(state['model'])
        if self._use_jit:
            self._jit_state = None   # rebuild functional state from network
        if self._optimizer is not None and state.get('opt') is not None:
            self._optimizer.set_state_dict(state['opt'])
        if self._scaler is not None and state.get('scaler') is not None:
            self._scaler.load_state_dict(state['scaler'])
        if self._nan_guard is not None and \
                state.get('nan_guard') is not None:
            self._nan_guard.load_state_dict(state['nan_guard'])
        rng = {'save_point': state.get('rng'),
               'epoch_start': state.get('epoch_start_rng')}
        return int(meta.get('epoch', 0)), int(meta.get('step_in_epoch', 0)), \
            rng

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, _from_fit=False):
        loader = self._to_loader(eval_data, batch_size, False, False,
                                 num_workers)
        for m in self._metrics:
            m.reset()
        total_loss, n = 0.0, 0
        for batch in loader:
            ins, lbs = self._split_batch(batch)
            losses, _ = self.eval_batch(ins, lbs)
            if losses:
                total_loss += losses[0]
                n += 1
        logs = {}
        if n:
            logs['loss'] = total_loss / n
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            for nm, v in zip(names, vals):
                logs[nm] = v
        from .. import observability as _obs
        if _obs.enabled():
            # the eval numbers reach the event log whether or not the
            # console rendering below is on (GL014: a metric that only
            # exists on stdout is invisible to every scrape)
            _obs.event('eval_result', **{
                k: float(v) for k, v in logs.items()
                if isinstance(v, (int, float))})
        if verbose:
            # graftlint: disable=GL014 — user-requested verbose console
            # output; the same values land on the event log above
            print(' - '.join(f"{k}: {v:.4f}" for k, v in logs.items()))
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                callbacks=None, verbose=1):
        loader = self._to_loader(test_data, batch_size, False, False,
                                 num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            outputs.append(self.predict_batch(ins))
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in
                    range(n_out)]
        return outputs

    # -- persistence --------------------------------------------------------
    def save(self, path, training=True):
        """training=True: .pdparams (+.pdopt). training=False: a runnable
        inference export via jit.save, using the Model's declared inputs
        as the InputSpec (reference hapi/model.py:993)."""
        self._sync_jit_state()
        from ..framework import save as fsave
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if not training:
            from .. import jit as _jit
            spec = self._inputs
            if spec is not None and not isinstance(spec, (list, tuple)):
                spec = [spec]
            if not spec:
                raise ValueError(
                    "Model.save(training=False) exports a runnable "
                    "inference artifact and needs input specs: construct "
                    "the Model with inputs=[InputSpec(...)] (raising now "
                    "instead of writing a non-runnable artifact)")
            was_training = self.network.training
            self.network.eval()
            try:
                _jit.save(self.network, path, input_spec=spec)
            finally:
                if was_training:
                    self.network.train()
            # jit.save records export failures instead of raising; surface
            # them NOW rather than at deployment load time
            import pickle as _pickle
            with open(path + '.pdmodel', 'rb') as f:
                meta = _pickle.load(f)
            if 'exported' not in meta:
                raise RuntimeError(
                    "Model.save(training=False): inference export failed "
                    "(%s) — the artifact would not be runnable"
                    % meta.get('export_error', 'unknown'))
            return
        fsave(self.network.state_dict(), path + '.pdparams')
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + '.pdopt')

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import load as fload
        state = fload(path + '.pdparams')
        self.network.set_state_dict(state)
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + '.pdopt'):
            self._optimizer.set_state_dict(fload(path + '.pdopt'))

    def test_batch(self, inputs):
        """Reference alias of predict_batch (hapi/model.py:956)."""
        return self.predict_batch(inputs)

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        return summary(self.network, input_size, dtypes=dtype)

    # -- helpers ------------------------------------------------------------
    def _tensor(self, x):
        return x if isinstance(x, Tensor) else to_tensor(np.asarray(x))

    def _to_list(self, x):
        if x is None:
            return []
        return list(x) if isinstance(x, (list, tuple)) else [x]

    def _split_batch(self, batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return self._to_list(batch[0]), self._to_list(batch[1])
            return self._to_list(batch[0]), []
        return [batch], []

    def _to_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    def _update_metrics(self, outs, labels):
        results = []
        for m in self._metrics:
            computed = m.compute(outs[0],
                                 *[self._tensor(l) for l in labels])
            if isinstance(computed, tuple) and not isinstance(computed, Tensor):
                res = m.update(*computed)
            else:
                res = m.update(computed)
            results.append(res)
        return results
