"""Model summary. Parity: python/paddle/hapi/model_summary.py."""
import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..core import autograd


def summary(net, input_size=None, dtypes=None, input=None):
    rows = []
    hooks = []

    def register(layer, prefix):
        def hook(l, inputs, output):
            out = output[0] if isinstance(output, (list, tuple)) else output
            n_params = sum(p.size for p in l.parameters(include_sublayers=False))
            rows.append((f"{type(l).__name__}", prefix,
                         list(out.shape) if isinstance(out, Tensor) else '-',
                         n_params))
        hooks.append(layer.register_forward_post_hook(hook))

    for name, l in net.named_sublayers():
        if not list(l.named_children()):
            register(l, name)

    if input is None:
        if isinstance(input_size, tuple) and input_size and \
                isinstance(input_size[0], (tuple, list)):
            sizes = input_size
        else:
            sizes = [input_size]
        dts = dtypes or ['float32'] * len(sizes)
        inputs = [to_tensor(np.zeros([1 if s in (None, -1) else s
                                      for s in size], dtype=dt))
                  for size, dt in zip(sizes, dts)]
    else:
        inputs = input if isinstance(input, (list, tuple)) else [input]

    was_training = net.training
    net.eval()
    with autograd.no_grad():
        net(*inputs)
    if was_training:
        net.train()
    for h in hooks:
        h.remove()

    total = sum(p.size for p in net.parameters())
    trainable = sum(p.size for p in net.parameters() if p.trainable)
    header = f"{'Layer (type)':<28}{'Name':<28}{'Output Shape':<22}{'Param #':<12}"
    print('-' * len(header))
    print(header)
    print('=' * len(header))
    for t, n, s, p in rows:
        print(f"{t:<28}{n:<28}{str(s):<22}{p:<12}")
    print('=' * len(header))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print('-' * len(header))
    return {'total_params': int(total), 'trainable_params': int(trainable)}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough FLOPs: 2 * params touched per conv/linear per output element."""
    from .. import nn
    total = [0]
    hooks = []

    def conv_hook(l, inputs, output):
        out = output[0] if isinstance(output, (list, tuple)) else output
        k = int(np.prod(l._kernel_size))
        cin = l._in_channels // l._groups
        spatial = int(np.prod(out.shape[2:]))
        total[0] += 2 * k * cin * l._out_channels * spatial * out.shape[0]

    def linear_hook(l, inputs, output):
        total[0] += 2 * l._in_features * l._out_features

    for l in net.sublayers():
        if isinstance(l, (nn.Conv1D, nn.Conv2D, nn.Conv3D)):
            hooks.append(l.register_forward_post_hook(conv_hook))
        elif isinstance(l, nn.Linear):
            hooks.append(l.register_forward_post_hook(linear_hook))

    x = to_tensor(np.zeros([1 if s in (None, -1) else s for s in input_size],
                           dtype='float32'))
    with autograd.no_grad():
        net.eval()
        net(x)
    for h in hooks:
        h.remove()
    if print_detail:
        print(f"Total FLOPs: {total[0]:,}")
    return total[0]
