"""Progress bar. Parity: python/paddle/hapi/progressbar.py."""
import sys

from ..observability import Stopwatch


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, start=True,
                 file=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self.file = file
        self._values = {}
        self._sw = Stopwatch()
        self._last_update = 0

    def update(self, current_num, values=None):
        if values:
            for k, v in values:
                self._values[k] = v
        if self._verbose == 0:
            return
        info = ' - '.join(f"{k}: {v:.4f}" if isinstance(v, float) else
                          f"{k}: {v}" for k, v in self._values.items())
        if self._num:
            bar_len = int(self._width * current_num / self._num)
            bar = '=' * bar_len + '.' * (self._width - bar_len)
            msg = f"\rstep {current_num}/{self._num} [{bar}] {info}"
        else:
            msg = f"\rstep {current_num} {info}"
        self.file.write(msg)
        if self._num and current_num >= self._num:
            self.file.write(f" - {self._sw.elapsed():.0f}s\n")
        self.file.flush()
        self._last_update = self._sw.elapsed()

    def start(self):
        self._sw.restart()
