"""Incubating features. Parity: python/paddle/incubate + fluid/incubate."""
from . import checkpoint
from ..distributed import fleet
