"""Incubating features. Parity: python/paddle/incubate + fluid/incubate."""
from . import checkpoint
from ..distributed import fleet

from . import complex
from . import data_generator
from . import custom_op
from .custom_op import register_op

from ..fluid.contrib import reader  # noqa: E402,F401  (paddle.incubate.reader)
