"""Auto checkpoint/resume. Parity: fluid/incubate/checkpoint/auto_checkpoint.py.

TPU-first: orbax-backed async checkpointing of model+optimizer state.
"""
import os

__all__ = ['AutoCheckpoint', 'save_checkpoint', 'load_checkpoint']


def save_checkpoint(path, layer=None, optimizer=None, step=0, use_orbax=True):
    from ..framework import save
    os.makedirs(path, exist_ok=True)
    meta = {'step': int(step)}
    if layer is not None:
        save(layer.state_dict(), os.path.join(path, 'model.pdparams'))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(path, 'opt.pdopt'))
    import json
    with open(os.path.join(path, 'meta.json'), 'w') as f:
        json.dump(meta, f)


def load_checkpoint(path, layer=None, optimizer=None):
    from ..framework import load
    import json
    meta_path = os.path.join(path, 'meta.json')
    if not os.path.exists(meta_path):
        return None
    with open(meta_path) as f:
        meta = json.load(f)
    if layer is not None:
        layer.set_state_dict(load(os.path.join(path, 'model.pdparams')))
    if optimizer is not None and os.path.exists(os.path.join(path, 'opt.pdopt')):
        optimizer.set_state_dict(load(os.path.join(path, 'opt.pdopt')))
    return meta


class AutoCheckpoint:
    """Periodic checkpoint + auto-resume helper."""

    def __init__(self, path, layer=None, optimizer=None, save_every=100):
        self.path = path
        self.layer = layer
        self.optimizer = optimizer
        self.save_every = save_every
        self.step = 0

    def resume(self):
        meta = load_checkpoint(self.path, self.layer, self.optimizer)
        if meta:
            self.step = meta['step']
        return self.step

    def tick(self):
        self.step += 1
        if self.step % self.save_every == 0:
            save_checkpoint(self.path, self.layer, self.optimizer, self.step)
