"""Auto checkpoint/resume. Parity: fluid/incubate/checkpoint/auto_checkpoint.py.

TPU-first design:
- step-numbered checkpoint directories with a ``latest`` pointer file, each
  committed via atomic rename so a crash mid-write can never corrupt the
  checkpoint a resume would read;
- genuinely asynchronous saves (``async_save=True`` / ``AsyncCheckpointer``):
  the device->host snapshot happens on the caller thread (so the training loop
  can immediately mutate params — donated buffers are already copied out), and
  serialization + disk IO run on a background writer thread, overlapping the
  next training steps the way the reference overlaps its trainer thread with
  the checkpoint RPC (auto_checkpoint.py's _thread saver).
"""
import json
import os
import shutil
import threading

__all__ = ['AutoCheckpoint', 'AsyncCheckpointer', 'save_checkpoint',
           'load_checkpoint']


def _snapshot(layer=None, optimizer=None, step=0):
    """Device->host copy of all state on the caller thread.

    After this returns, the live params/opt-state may be mutated freely; the
    snapshot is plain numpy payloads with no aliasing of device buffers.
    """
    from ..framework import _to_saveable
    snap = {'meta': {'step': int(step)}}
    if layer is not None:
        snap['model'] = _to_saveable(layer.state_dict())
    if optimizer is not None:
        snap['opt'] = _to_saveable(optimizer.state_dict())
    return snap


def _write_snapshot(path, snap):
    """Serialize a snapshot into ``path/ckpt-<step>`` via atomic rename."""
    import pickle
    step = snap['meta']['step']
    os.makedirs(path, exist_ok=True)
    final = os.path.join(path, 'ckpt-%d' % step)
    tmp = os.path.join(path, '.tmp-ckpt-%d-%d' % (step, os.getpid()))
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    def _dump(name, writer):
        # fsync before the commit rename: the rename's metadata must never
        # reach disk ahead of the payload pages, or a power loss could leave
        # a committed-but-torn checkpoint that resume would trust.
        # atomic-ok: staged inside the tmp dir, committed via os.rename below
        with open(os.path.join(tmp, name), 'wb') as f:
            writer(f)
            f.flush()
            os.fsync(f.fileno())

    if 'model' in snap:
        _dump('model.pdparams',
              lambda f: pickle.dump(snap['model'], f, protocol=4))
    if 'opt' in snap:
        _dump('opt.pdopt', lambda f: pickle.dump(snap['opt'], f, protocol=4))
    _dump('meta.json', lambda f: f.write(json.dumps(snap['meta']).encode()))
    if os.path.isdir(final):
        shutil.rmtree(final)
    os.rename(tmp, final)  # atomic commit of the checkpoint dir
    dir_fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(dir_fd)   # persist the rename itself
    finally:
        os.close(dir_fd)
    # atomically flip the 'latest' pointer
    ptr_tmp = os.path.join(path, '.latest.tmp')
    with open(ptr_tmp, 'w') as f:
        f.write('ckpt-%d' % step)
        f.flush()
        os.fsync(f.fileno())
    os.replace(ptr_tmp, os.path.join(path, 'latest'))
    return final


def _prune_old(path, max_keep):
    """Delete all but the newest ``max_keep`` committed checkpoints."""
    if not max_keep or not os.path.isdir(path):
        return
    steps = sorted(
        int(d[5:]) for d in os.listdir(path)
        if d.startswith('ckpt-') and d[5:].isdigit())
    for s in steps[:-max_keep]:
        shutil.rmtree(os.path.join(path, 'ckpt-%d' % s), ignore_errors=True)


class AsyncCheckpointer:
    """Background-thread checkpoint writer.

    ``save()`` snapshots state synchronously (cheap device->host copies) and
    returns immediately; pickling and disk writes happen on a single worker
    thread. Overlapping saves are serialized in submission order. Worker
    failures are re-raised on the next ``save()``/``wait_until_finished()``.
    """

    def __init__(self, path, max_keep=None):
        self.path = path
        self.max_keep = max_keep
        self._submit = threading.Lock()  # serializes save() submissions
        self._lock = threading.Lock()    # guards _pending/_error
        self._pending = None   # thread handling the in-flight write, if any
        self._error = None

    def _raise_pending_error(self):
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    def save(self, layer=None, optimizer=None, step=0):
        # _submit makes concurrent save() calls atomic (wait+snapshot+spawn):
        # without it two callers could both observe no pending write and
        # orphan one writer thread, losing its error and its join.
        with self._submit:
            # graftlint: disable=GC003 — serializing save() THROUGH the
            # in-flight join is this lock's contract (comment above): a
            # second saver must wait out the previous write anyway, and
            # the join is the wait.
            self._wait_pending()
            self._raise_pending_error()
            snap = _snapshot(layer, optimizer, step)

            def _work():
                try:
                    _write_snapshot(self.path, snap)
                    _prune_old(self.path, self.max_keep)
                except BaseException as e:  # surfaced on next save/wait
                    with self._lock:
                        self._error = e

            t = threading.Thread(target=_work, name='paddle-tpu-ckpt',
                                 daemon=True)
            with self._lock:
                self._pending = t
            t.start()

    def _wait_pending(self):
        with self._lock:
            t = self._pending
        if t is not None:
            # tick-based join (watchdog): stays signal-interruptible while
            # a large checkpoint drains to disk
            from ..resilience.watchdog import join_thread
            join_thread(t, timeout=None)
            with self._lock:
                if self._pending is t:
                    self._pending = None

    def wait_until_finished(self):
        self._wait_pending()
        self._raise_pending_error()


_shared_checkpointers = {}
_shared_lock = threading.Lock()


def save_checkpoint(path, layer=None, optimizer=None, step=0,
                    async_save=False):
    """Write a step-numbered checkpoint under ``path``.

    ``async_save=True`` returns an :class:`AsyncCheckpointer` whose write is
    already in flight (call ``wait_until_finished()`` before process exit);
    otherwise the write is synchronous. Either way the commit is atomic.
    Repeated async saves to the same path share one checkpointer, so
    overlapping writes are serialized in submission order.
    """
    if async_save:
        key = os.path.abspath(path)
        with _shared_lock:
            ck = _shared_checkpointers.setdefault(key, AsyncCheckpointer(path))
        ck.save(layer, optimizer, step)
        return ck
    _write_snapshot(path, _snapshot(layer, optimizer, step))
    return None


def _resolve_latest(path):
    """Return the directory holding the newest committed checkpoint.

    The max step among committed ``ckpt-<step>`` dirs is authoritative (a dir
    only exists post-rename, so every one is complete); the ``latest`` pointer
    is a hint only — a slow out-of-order writer could leave it stale.
    """
    if os.path.isdir(path):
        steps = sorted(
            int(d[5:]) for d in os.listdir(path)
            if d.startswith('ckpt-') and d[5:].isdigit())
        if steps:
            return os.path.join(path, 'ckpt-%d' % steps[-1])
    if os.path.isfile(os.path.join(path, 'meta.json')):  # legacy flat layout
        return path
    return None


def load_checkpoint(path, layer=None, optimizer=None):
    """Restore the newest checkpoint under ``path``; returns its meta dict
    (or ``None`` when no committed checkpoint exists)."""
    from ..framework import load
    d = _resolve_latest(path)
    if d is None:
        return None
    with open(os.path.join(d, 'meta.json')) as f:
        meta = json.load(f)
    if layer is not None:
        layer.set_state_dict(load(os.path.join(d, 'model.pdparams')))
    if optimizer is not None and os.path.exists(os.path.join(d, 'opt.pdopt')):
        optimizer.set_state_dict(load(os.path.join(d, 'opt.pdopt')))
    return meta


class AutoCheckpoint:
    """Periodic async checkpoint + auto-resume helper.

    Saves every ``save_every`` ticks on a background thread, keeps the newest
    ``max_keep`` checkpoints, and ``resume()`` restores the latest committed
    one (partial/crashed writes are invisible thanks to the atomic commit).
    """

    def __init__(self, path, layer=None, optimizer=None, save_every=100,
                 max_keep=3):
        self.path = path
        self.layer = layer
        self.optimizer = optimizer
        self.save_every = save_every
        self.step = 0
        self._ck = AsyncCheckpointer(path, max_keep=max_keep)

    def resume(self):
        meta = load_checkpoint(self.path, self.layer, self.optimizer)
        if meta:
            self.step = meta['step']
        return self.step

    def tick(self):
        self.step += 1
        if self.step % self.save_every == 0:
            self._ck.save(self.layer, self.optimizer, self.step)

    def wait_until_finished(self):
        self._ck.wait_until_finished()
