"""incubate.complex: complex-tensor op namespace.

Parity: /root/reference/python/paddle/incubate/complex/ (tensor/math.py,
linalg.py, manipulation.py). TPU-first divergence: the reference carries a
ComplexVariable of two real tensors because fluid had no complex dtype;
here complex64/complex128 are NATIVE jax dtypes, so these functions are the
regular ops — the namespace exists so reference scripts import unchanged.
"""
from . import tensor
from .tensor import (elementwise_add, elementwise_sub, elementwise_mul,
                     elementwise_div, kron, trace, sum, matmul, reshape,
                     transpose)

__all__ = tensor.__all__
