"""Complex tensor ops (native complex dtypes; see package docstring)."""
from ...tensor.math import (kron, trace, sum, matmul)
from ...tensor.math import (elementwise_add, elementwise_sub,
                            elementwise_mul, elementwise_div)
from ...tensor.manipulation import reshape, transpose

__all__ = ['elementwise_add', 'elementwise_sub', 'elementwise_mul',
           'elementwise_div', 'kron', 'trace', 'sum', 'matmul',
           'reshape', 'transpose']
