"""Custom op registration: user-defined (e.g. Pallas) kernels as framework ops.

Parity: the reference's C++ custom-operator path (paddle/fluid/framework/
op_registry.h + load_op_library / utils.cpp_extension): users register a
compute function and optional gradient and the op becomes callable on
Tensors with autograd support. TPU-first: the "kernel" is any jax-traceable
callable — typically a pallas_call TPU kernel — wired into the eager tape
via jax.custom_vjp, so it works identically under eager, jit.to_static and
grad transforms.
"""
import jax

from ..core.tensor import Tensor, apply_op

__all__ = ['register_op', 'get_op', 'list_ops', 'CustomOpError']

_REGISTRY = {}


class CustomOpError(RuntimeError):
    pass


def register_op(name, fn, vjp_fwd=None, vjp_bwd=None, n_outputs=1,
                overwrite=False):
    """Register ``fn(*jax_arrays) -> array(s)`` as op ``name``.

    vjp_fwd/vjp_bwd: optional custom gradient pair with jax.custom_vjp
    semantics — fwd returns (out, residuals), bwd(residuals, cotangents)
    returns input cotangent tuple. Without them, jax autodiff differentiates
    straight through ``fn`` (fine for most pallas kernels built from
    differentiable primitives... supply the pair when the kernel uses
    non-differentiable tricks or a hand-written backward kernel is faster).

    Returns the Tensor-level callable (also retrievable via get_op(name)).
    """
    if name in _REGISTRY and not overwrite:
        raise CustomOpError(f"op '{name}' already registered")
    if (vjp_fwd is None) != (vjp_bwd is None):
        raise CustomOpError("provide both vjp_fwd and vjp_bwd or neither")

    has_vjp = vjp_fwd is not None
    kernel = fn
    if has_vjp:
        kernel = jax.custom_vjp(fn)
        kernel.defvjp(vjp_fwd, vjp_bwd)
    try:
        kernel.__name__ = name
    except AttributeError:
        pass

    def tensor_op(*args, **kwargs):
        tensors = [a if isinstance(a, Tensor) else Tensor(a) for a in args]
        if kwargs:
            if has_vjp:
                # jax.custom_vjp resolves kwargs into positional diff args,
                # which breaks a bwd that returns tensor cotangents only
                raise CustomOpError(
                    f"op '{name}': keyword args are unsupported with a "
                    f"custom vjp — close constants over the kernel or "
                    f"register a partial instead")
            def bound(*vals):
                return kernel(*vals, **kwargs)
            bound.__name__ = name
            return apply_op(bound, tuple(tensors), n_outputs=n_outputs)
        return apply_op(kernel, tuple(tensors), n_outputs=n_outputs)

    tensor_op.__name__ = name
    _REGISTRY[name] = tensor_op
    return tensor_op


def get_op(name):
    try:
        return _REGISTRY[name]
    except KeyError:
        raise CustomOpError(f"op '{name}' is not registered") from None


def list_ops():
    return sorted(_REGISTRY)
