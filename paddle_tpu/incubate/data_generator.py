"""Parameter-server training data generators.

Parity: /root/reference/python/paddle/fluid/incubate/data_generator/
(DataGenerator:28, MultiSlotStringDataGenerator:241,
MultiSlotDataGenerator:282). Emits the MultiSlotDataFeed text format
(`ids_num id1 id2 ...` per slot) — the interchange the reference's C++
DataFeed consumes; here the same lines feed the dense Dataset loaders.
"""
import sys

__all__ = ['DataGenerator', 'MultiSlotDataGenerator',
           'MultiSlotStringDataGenerator']


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 32
        self._proto_info = None
        self._line_limit = None

    def _set_line_limit(self, line_limit):
        self._line_limit = int(line_limit)

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # -- user hooks ---------------------------------------------------------
    def generate_sample(self, line):
        """Return a no-arg iterator over [(slot_name, values), ...]."""
        raise NotImplementedError(
            "generate_sample() must be implemented by the subclass")

    def generate_batch(self, samples):
        def local_iter():
            for sample in samples:
                yield sample
        return local_iter

    # -- drivers ------------------------------------------------------------
    def run_from_memory(self):
        """Process in-memory samples (generate_sample(None)) to stdout."""
        batch_samples = []
        line_iter = self.generate_sample(None)
        for user_parsed_line in line_iter():
            if user_parsed_line is None:
                continue
            batch_samples.append(user_parsed_line)
            if len(batch_samples) == self.batch_size_:
                self._flush(batch_samples)
                batch_samples = []
        if batch_samples:
            self._flush(batch_samples)

    def run_from_stdin(self):
        """Process stdin lines through generate_sample to stdout."""
        batch_samples = []
        for n, line in enumerate(sys.stdin, 1):
            if self._line_limit and n > self._line_limit:
                break
            line_iter = self.generate_sample(line)
            for user_parsed_line in line_iter():
                if user_parsed_line is None:
                    continue
                batch_samples.append(user_parsed_line)
                if len(batch_samples) == self.batch_size_:
                    self._flush(batch_samples)
                    batch_samples = []
        if batch_samples:
            self._flush(batch_samples)

    def _flush(self, batch_samples):
        batch_iter = self.generate_batch(batch_samples)
        for sample in batch_iter():
            sys.stdout.write(self._gen_str(sample))

    def _gen_str(self, line):
        raise NotImplementedError


class MultiSlotStringDataGenerator(DataGenerator):
    def _gen_str(self, line):
        """[(name, [str, ...]), ...] -> 'n v1 .. vn m w1 .. wm\\n'."""
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type, "
                "e.g. [('words', ['1926', '08', '17']), ('label', ['1'])]")
        parts = []
        for name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    def _gen_str(self, line):
        """[(name, [int|float, ...]), ...] with proto_info tracking."""
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type, "
                "e.g. [('words', [1926, 8, 17]), ('label', [1])]")
        if self._proto_info is None:
            self._proto_info = []
            for name, elements in line:
                if not isinstance(name, str):
                    raise ValueError(f"name {name!r} must be a str")
                if not isinstance(elements, list) or not elements:
                    raise ValueError(
                        f"slot {name!r}: elements must be a non-empty list")
                dtype = "float" if any(isinstance(e, float)
                                       for e in elements) else "uint64"
                self._proto_info.append((name, dtype))
        else:
            if len(line) != len(self._proto_info):
                raise ValueError(
                    f"field count changed: {len(line)} vs "
                    f"{len(self._proto_info)}")
            # promote a slot to float once a float shows up (the
            # reference's proto updating rule)
            for i, (name, elements) in enumerate(line):
                if self._proto_info[i][1] == "uint64" and any(
                        isinstance(e, float) for e in elements):
                    self._proto_info[i] = (name, "float")
        parts = []
        for name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"
