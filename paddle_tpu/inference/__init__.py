"""Inference engine: AOT-compiled executable caching + Predictor.

Parity: the reference's inference/ stack (AnalysisPredictor + its
serialized program/optimization caches; paddle/fluid/inference/api). On
TPU the expensive artifact is not an optimized subgraph but the XLA
executable, so the cache layer works at that level:

- ``enable_compilation_cache(dir)`` — turns on XLA's persistent
  compilation cache (every jit in the process, keyed by HLO fingerprint;
  survives process restarts, the analogue of the reference's
  serialized-program cache directory).
- ``AOTCompiledFunction`` — explicit ahead-of-time lower+compile of one
  function for fixed shapes, serializable to a single file with
  ``jax.experimental.serialize_executable`` (the analogue of shipping a
  compiled inference engine; reloading skips tracing AND compilation).
- ``Predictor`` — save_inference_model dir -> ready-to-run engine with
  feed/fetch names (AnalysisPredictor analogue), jit-cached per feed
  shape, optionally backed by the persistent cache.
- ``load_inference_model(dirname)`` — THE documented load path: one call
  that turns a ``save_inference_model`` directory into a ready
  ``Predictor``. The serving engine (``paddle_tpu.serving``) and direct
  users share it, so an export that loads here is guaranteed to serve.
"""
import os
import pickle

import numpy as np
import jax

from ..core.tensor import Tensor

__all__ = ['enable_compilation_cache', 'AOTCompiledFunction', 'Predictor',
           'load_inference_model']


def enable_compilation_cache(cache_dir):
    """Enable XLA's persistent compilation cache under ``cache_dir``.

    Compiled executables for every jit (bench steps, Executor programs,
    Predictor runs) are written there and reused across processes; the
    first warm-start skips XLA compilation entirely.
    """
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update('jax_compilation_cache_dir', cache_dir)
    # cache every computation, however small/fast to compile
    jax.config.update('jax_persistent_cache_min_entry_size_bytes', 0)
    jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.0)
    # jax initializes the persistent cache AT MOST ONCE, on the first
    # compile. importing paddle_tpu jit-compiles helpers before any user
    # code runs, so by the time this function is called the cache was
    # already initialized as DISABLED (no dir configured) and the config
    # updates above are silently ignored — every entry "written" is
    # dropped with "cache is disabled/not initialized". reset_cache()
    # discards that verdict so the next compile re-initializes against
    # cache_dir. Guarded: the private module moves between jax versions,
    # and an older jax without it initializes lazily enough not to need it.
    try:
        from jax._src import compilation_cache as _cc
        _cc.reset_cache()
    except (ImportError, AttributeError):
        pass
    return cache_dir


def _unwrap(a):
    if isinstance(a, Tensor):
        return a._value
    return a


class AOTCompiledFunction:
    """One function, one set of input shapes, compiled ahead of time.

    ``trace(fn, *example_args)`` lowers + compiles now;
    ``save(path)``/``load(path)`` serialize the compiled executable so a
    serving process runs without tracing or compiling (same
    backend/topology required, as with any native executable).
    """

    def __init__(self, compiled):
        self._compiled = compiled

    @classmethod
    def trace(cls, fn, *example_args):
        vals = tuple(_unwrap(a) for a in example_args)
        lowered = jax.jit(fn).lower(*vals)
        return cls(lowered.compile())

    def __call__(self, *args):
        vals = tuple(_unwrap(a) for a in args)
        # a deserialized executable requires inputs already placed per its
        # compiled shardings (a fresh-traced one commits them itself)
        shardings = getattr(self._compiled, 'input_shardings', None)
        if shardings is not None:
            vals = tuple(jax.device_put(v, s)
                         for v, s in zip(vals, shardings[0]))
        out = self._compiled(*vals)
        if isinstance(out, (tuple, list)):
            return type(out)(Tensor(o) for o in out)
        return Tensor(out)

    @property
    def in_avals(self):
        return self._compiled.in_avals

    def cost_analysis(self):
        return self._compiled.cost_analysis()

    def save(self, path):
        from jax.experimental import serialize_executable as se
        payload = se.serialize(self._compiled)   # (bytes, in_tree, out_tree)
        arg_shardings = self._compiled.input_shardings[0]
        n_devices = (len(arg_shardings[0].device_set)
                     if arg_shardings else 1)
        from ..resilience.atomic_io import atomic_pickle_dump
        atomic_pickle_dump({'backend': jax.default_backend(),
                            'n_devices': n_devices,
                            'payload': payload}, path)
        return path

    @classmethod
    def load(cls, path):
        from jax.experimental import serialize_executable as se
        with open(path, 'rb') as f:
            blob = pickle.load(f)
        if blob['backend'] != jax.default_backend():
            raise RuntimeError(
                "AOT executable was compiled for backend %r but this "
                "process runs %r — recompile with trace()"
                % (blob['backend'], jax.default_backend()))
        serialized, in_tree, out_tree = blob['payload']
        n = blob.get('n_devices') or 1
        if n > len(jax.devices()):
            raise RuntimeError(
                "AOT executable needs %d device(s); %d available"
                % (n, len(jax.devices())))
        # deserialize onto exactly the compiled device count — the default
        # would map onto every local device and then reject the args
        # (execution_devices is newer than some supported jax versions;
        # those versions also default to the compiled device assignment,
        # so omitting it is correct there, not just tolerated). Feature-
        # detect via the signature: a blanket except TypeError would also
        # swallow unrelated TypeErrors from inside deserialization.
        import inspect
        kwargs = {}
        try:
            if 'execution_devices' in inspect.signature(
                    se.deserialize_and_load).parameters:
                kwargs['execution_devices'] = jax.devices()[:n]
        except (TypeError, ValueError):
            pass
        return cls(se.deserialize_and_load(serialized, in_tree, out_tree,
                                           **kwargs))


class Predictor:
    """Inference engine over a save_inference_model directory.

    run(feed_dict) -> list of fetch arrays. The whole fetch subgraph runs
    as one jit computation per feed-shape signature; pass
    ``cache_dir`` to persist compiled executables across processes.
    """

    def __init__(self, dirname, model_filename=None, params_filename=None,
                 cache_dir=None):
        if cache_dir:
            enable_compilation_cache(cache_dir)
        with open(os.path.join(dirname, model_filename or '__model__'),
                  'rb') as f:
            meta = pickle.load(f)
        with open(os.path.join(dirname, params_filename or '__params__'),
                  'rb') as f:
            params = pickle.load(f)
        self._feed_names = list(meta['feed_names'])
        self._fetch_names = list(meta['fetch_names'])
        if 'exported' not in meta:
            raise RuntimeError(
                "model dir has no portable export (save_inference_model "
                "recorded: %s) — re-export it"
                % meta.get('export_error', 'unknown reason'))
        import jax.export  # noqa: F401 — lazy submodule: a bare
        # `import jax` does not bind the attribute
        self._exported = jax.export.deserialize(
            bytearray(meta['exported']['blob']))
        self._param_vals = [np.asarray(params[n])
                            for n in meta['exported']['param_names']]
        self._feed_dtypes = [np.dtype(d) for d in
                             meta['exported'].get(
                                 'feed_dtypes',
                                 ['float32'] * len(self._feed_names))]
        # run through the persistent compile tier: in-memory jit caching
        # per feed signature always; against a bound compilecache dir the
        # executable is AOT-deserialized/committed per signature, so a
        # fresh process (or a serving replica registering this predictor
        # with artifact_dir=) replays it with zero compiles
        from .. import compilecache as _cc
        self._call = _cc.CachedJit(
            lambda feed_vals, param_vals:
                self._exported.call(feed_vals, param_vals),
            auto_label='predictor.%s' % os.path.basename(
                os.path.abspath(dirname)),
            kind='predictor', meta={'dir': os.path.basename(
                os.path.abspath(dirname))})

    @property
    def feed_names(self):
        return list(self._feed_names)

    @property
    def fetch_names(self):
        return list(self._fetch_names)

    def run(self, feed):
        """feed: dict name -> array (numpy/Tensor). Returns numpy arrays
        in fetch order. Each new feed-shape signature compiles once (use
        cache_dir to persist those compilations across processes)."""
        feed = {k: (v.numpy() if isinstance(v, Tensor) else np.asarray(v))
                for k, v in feed.items()}
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise ValueError("Predictor.run: missing feeds %s" % missing)
        # cast to the exported dtypes (numpy defaults to float64/int64,
        # which the export was not built for) — same as Executor.run
        feed_vals = [np.asarray(feed[n], dtype=dt)
                     for n, dt in zip(self._feed_names, self._feed_dtypes)]
        outs = self._call(feed_vals, self._param_vals)
        fetched = [np.asarray(o) for o in outs]
        from .. import observability as _obs
        if _obs.enabled():
            _obs.record_host_transfer(sum(a.nbytes for a in fetched),
                                      kind='predictor.fetch')
        return fetched


def load_inference_model(dirname, model_filename=None, params_filename=None,
                         cache_dir=None):
    """Load a ``save_inference_model`` directory into a ready ``Predictor``.

    The standalone-process analogue of ``static.io.load_inference_model``
    (which rebinds params into the *current* Program and therefore only
    works in the process that built the graph — the save/load asymmetry
    this entry point closes). Use this one everywhere a fresh process
    serves an exported model; ``paddle_tpu.serving`` registers its models
    through the same call::

        predictor = inference.load_inference_model('model_dir')
        engine.register('m', predictor=predictor,
                        example={'x': np.zeros((16,), np.float32)})
    """
    return Predictor(dirname, model_filename=model_filename,
                     params_filename=params_filename, cache_dir=cache_dir)
