"""Torch interop: state-dict key/layout mapping.

Parity: SURVEY §2.8.6 (torch-style state dict key mapping helper). The
reference ecosystem ships torch checkpoints for many model zoos; this
module converts them to/from this framework's state dicts:

- key renames: BatchNorm ``running_mean``/``running_var`` <-> the
  ``_mean``/``_variance`` buffer names used here; torch-only bookkeeping
  (``num_batches_tracked``) is dropped;
- layout: torch ``nn.Linear`` stores (out_features, in_features) while
  this framework stores (in, out) — 2-D weights are transposed when the
  target shape says so (shape-guided, so conv kernels and square matrices
  that already match are left alone);
- values arrive as anything numpy can consume (torch tensors included via
  ``.detach().cpu().numpy()``).
"""
import numpy as np

__all__ = ['torch_key_map', 'from_torch_state_dict', 'to_torch_state_dict',
           'load_torch_state_dict']

_TORCH_TO_PADDLE_SUFFIX = {
    'running_mean': '_mean',
    'running_var': '_variance',
}
_DROP_SUFFIXES = ('num_batches_tracked',)


def _to_numpy(v):
    if hasattr(v, 'detach'):          # torch tensor, no hard torch dep
        v = v.detach().cpu().numpy()
    return np.asarray(v)


def torch_key_map(torch_keys, paddle_keys):
    """Map torch key -> paddle key.

    Exact matches after suffix renaming win; the remainder is matched
    positionally within the (stable) ordering of the unmatched keys —
    torch modules and their ports enumerate parameters in the same
    definition order, which is what makes the positional fallback sound.
    """
    renamed = {}
    for tk in torch_keys:
        head, _, tail = tk.rpartition('.')
        if tail in _DROP_SUFFIXES:
            continue
        tail = _TORCH_TO_PADDLE_SUFFIX.get(tail, tail)
        renamed[tk] = (head + '.' + tail) if head else tail

    paddle_set = set(paddle_keys)
    mapping = {}
    unmatched_t, used = [], set()
    for tk, guess in renamed.items():
        if guess in paddle_set and guess not in used:
            mapping[tk] = guess
            used.add(guess)
        else:
            unmatched_t.append(tk)
    unmatched_p = [pk for pk in paddle_keys if pk not in used]
    if unmatched_t or unmatched_p:
        # positional pairing is only sound when both sides line up 1:1 —
        # a count mismatch would shift every later pair onto the wrong
        # parameter, so fail loudly instead
        if len(unmatched_t) != len(unmatched_p):
            raise ValueError(
                "torch_key_map: %d torch key(s) and %d target key(s) left "
                "after name matching cannot be paired positionally "
                "(torch: %s; target: %s)"
                % (len(unmatched_t), len(unmatched_p),
                   unmatched_t[:4], unmatched_p[:4]))
        for tk, pk in zip(unmatched_t, unmatched_p):
            mapping[tk] = pk
    return mapping


def _linear_weight_keys(layer):
    """state_dict keys holding Linear weights (these need the (out,in) ->
    (in,out) transpose even when square, where shape can't tell)."""
    from .nn.layer.common import Linear
    keys = set()
    for name, sub in layer.named_sublayers(include_self=True):
        if isinstance(sub, Linear):
            keys.add((name + '.' if name else '') + 'weight')
    return keys


def from_torch_state_dict(torch_sd, reference_sd, linear_keys=()):
    """torch state dict -> framework state dict (numpy values).

    reference_sd: the target layer's ``state_dict()`` (used for key names
    and shape-guided transposes). linear_keys: target keys known to be
    Linear weights — always transposed, covering the square case where
    shapes alone cannot reveal the torch (out, in) layout;
    ``load_torch_state_dict`` fills this from the layer automatically.
    """
    ref_shapes = {k: tuple(v.shape) for k, v in reference_sd.items()}
    mapping = torch_key_map(list(torch_sd.keys()), list(reference_sd.keys()))
    linear_keys = set(linear_keys)
    out = {}
    for tk, pk in mapping.items():
        v = _to_numpy(torch_sd[tk])
        want = ref_shapes.get(pk)
        if pk in linear_keys and v.ndim == 2:
            v = v.T                        # torch Linear (out,in) -> (in,out)
        if want is not None and tuple(v.shape) != want:
            if v.ndim == 2 and tuple(v.T.shape) == want:
                v = v.T
            elif v.size == int(np.prod(want)):
                v = v.reshape(want)
            else:
                raise ValueError(
                    "cannot adapt torch param %r %s to %r %s"
                    % (tk, tuple(v.shape), pk, want))
        out[pk] = v
    return out


def load_torch_state_dict(layer, torch_sd, strict=True):
    """Load a torch state dict into ``layer`` in place; returns the layer."""
    own = layer.state_dict()
    converted = from_torch_state_dict(torch_sd, own,
                                      linear_keys=_linear_weight_keys(layer))
    if strict:
        missing = sorted(set(own) - set(converted))
        if missing:
            raise ValueError(
                "torch checkpoint is missing %d parameter(s): %s"
                % (len(missing), missing[:5]))
    layer.set_state_dict(converted)
    return layer


def to_torch_state_dict(layer):
    """Framework layer -> torch-convention state dict (numpy values):
    reverse renames + Linear transpose + synthesized zero
    ``num_batches_tracked`` per BatchNorm, consumable by
    ``torch_module.load_state_dict`` (strict) after ``torch.from_numpy``."""
    inv = {v: k for k, v in _TORCH_TO_PADDLE_SUFFIX.items()}
    out = {}
    linear_weights = _linear_weight_keys(layer)
    for k, v in layer.state_dict().items():
        head, _, tail = k.rpartition('.')
        tail = inv.get(tail, tail)
        arr = np.asarray(v.numpy())
        if k in linear_weights and arr.ndim == 2:
            arr = arr.T
        out[(head + '.' + tail) if head else tail] = arr
    # torch BatchNorm carries num_batches_tracked which has no analogue
    # here; emit zeros so strict load_state_dict round-trips
    for name, sub in layer.named_sublayers(include_self=True):
        if '_mean' in getattr(sub, '_buffers', {}):
            prefix = name + '.' if name else ''
            out[prefix + 'num_batches_tracked'] = np.array(0, np.int64)
    return out
