"""paddle_tpu.io. Parity: python/paddle/io/__init__.py."""
from .dataset import (Dataset, IterableDataset, TensorDataset, ComposeDataset,
                      ChainDataset, ConcatDataset, Subset, random_split)
from .sampler import (Sampler, SequenceSampler, RandomSampler,
                      WeightedRandomSampler, BatchSampler,
                      DistributedBatchSampler)
from .dataloader import (DataLoader, DataLoaderWorkerError, DevicePrefetcher,
                         default_collate_fn, default_convert_fn)
# fluid.io reader-decorator compat (reference fluid/io.py does
# `from paddle.reader import *`)
from ..reader import (map_readers, shuffle, chain, buffered, compose,
                      firstn, xmap_readers, cache, multiprocess_reader,
                      ComposeNotAligned)

__all__ = ['Dataset', 'IterableDataset', 'TensorDataset', 'ComposeDataset',
           'ChainDataset', 'ConcatDataset', 'Subset', 'random_split',
           'Sampler', 'SequenceSampler', 'RandomSampler',
           'WeightedRandomSampler', 'BatchSampler', 'DistributedBatchSampler',
           'DataLoader', 'DataLoaderWorkerError', 'DevicePrefetcher',
           'default_collate_fn', 'default_convert_fn',
           'map_readers', 'shuffle', 'chain', 'buffered', 'compose',
           'firstn', 'xmap_readers', 'cache', 'multiprocess_reader',
           'ComposeNotAligned']

# 2.0-beta top-level re-exports (reference io/__init__.py)
from ..batch import batch  # noqa: F401,E402
from ..framework import save, load  # noqa: F401,E402
from ..static.io import (save_inference_model,  # noqa: F401,E402
                         load_inference_model)


def get_worker_info():
    """DataLoader worker context. Returns None outside a worker (the
    reference contract); inside our process workers, the rank env set by
    the pool is surfaced as a lightweight info object."""
    import os

    class _WorkerInfo:
        def __init__(self, wid, num):
            self.id = wid
            self.num_workers = num

    wid = os.environ.get('PADDLE_DATALOADER_WORKER_ID')
    if wid is None:
        return None
    return _WorkerInfo(int(wid),
                       int(os.environ.get('PADDLE_DATALOADER_NUM_WORKERS',
                                          '1')))


def load_program_state(model_path, var_list=None):
    """Load a saved static program state dict (io.py parity)."""
    import numpy as _np
    from ..framework import load as _load
    state = _load(model_path if model_path.endswith('.pdparams')
                  else model_path + '.pdparams')
    return {k: _np.asarray(v) for k, v in state.items()}


def set_program_state(program, state_dict):
    """Bind a loaded state dict onto a Program's parameters."""
    import jax.numpy as _jnp
    for v in program.list_vars():
        if v.name in state_dict and v.concrete is not None:
            v.concrete._inplace_value(
                _jnp.asarray(state_dict[v.name]).astype(v.concrete.dtype))
