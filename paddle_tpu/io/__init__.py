"""paddle_tpu.io. Parity: python/paddle/io/__init__.py."""
from .dataset import (Dataset, IterableDataset, TensorDataset, ComposeDataset,
                      ChainDataset, ConcatDataset, Subset, random_split)
from .sampler import (Sampler, SequenceSampler, RandomSampler,
                      WeightedRandomSampler, BatchSampler,
                      DistributedBatchSampler)
from .dataloader import DataLoader, default_collate_fn, default_convert_fn
# fluid.io reader-decorator compat (reference fluid/io.py does
# `from paddle.reader import *`)
from ..reader import (map_readers, shuffle, chain, buffered, compose,
                      firstn, xmap_readers, cache, multiprocess_reader,
                      ComposeNotAligned)

__all__ = ['Dataset', 'IterableDataset', 'TensorDataset', 'ComposeDataset',
           'ChainDataset', 'ConcatDataset', 'Subset', 'random_split',
           'Sampler', 'SequenceSampler', 'RandomSampler',
           'WeightedRandomSampler', 'BatchSampler', 'DistributedBatchSampler',
           'DataLoader', 'default_collate_fn', 'default_convert_fn',
           'map_readers', 'shuffle', 'chain', 'buffered', 'compose',
           'firstn', 'xmap_readers', 'cache', 'multiprocess_reader',
           'ComposeNotAligned']
