"""DataLoader. Parity: python/paddle/fluid/reader.py:DataLoader +
fluid/dataloader/dataloader_iter.py.

TPU-first: worker threads/processes produce numpy batches; a double-buffered
prefetcher overlaps host batch assembly and host->HBM transfer with device
compute (the reference overlaps via pinned-memory + CUDA streams; here the
async dispatch of jax.device_put plays that role). A native C++ prefetch ring
(csrc/prefetch.cpp) backs the queue when built.

Self-healing (docs/RESILIENCE.md, "Distributed fault tolerance"): a worker
that raises propagates the exception to the consumer instead of dying
silently; a worker that hangs or is killed trips a deadlock watchdog
(bounded queue waits + liveness checks, budget = ``timeout`` seconds or
``PADDLE_TPU_DATA_TIMEOUT``); poisoned samples are quarantined up to a
bounded skip budget (``skip_bad_samples`` / ``PADDLE_TPU_DATA_SKIP_BUDGET``)
with a per-index report; crashed process workers are respawned up to
``worker_max_restarts`` times.
"""
import itertools
import os
import queue
import threading

import numpy as np

from ..core.tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler, SequenceSampler, RandomSampler
from .. import observability as _obs
from ..resilience import watchdog as _watchdog

__all__ = ['DataLoader', 'DevicePrefetcher', 'default_collate_fn',
           'default_convert_fn', 'DataLoaderWorkerError']

# consumer-side stall budget when DataLoader(timeout=0): generous enough
# for any real batch assembly, small enough that a wedged pipeline fails
# the job the same hour it wedges
_DEFAULT_WATCHDOG_S = 300.0


class DataLoaderWorkerError(RuntimeError):
    """A DataLoader worker failed (raised, hung past the watchdog budget,
    or died) and the loader could not self-heal within its budgets.
    ``quarantined`` carries the (index, error) pairs skipped so far."""

    def __init__(self, message, quarantined=()):
        self.quarantined = list(quarantined)
        if self.quarantined:
            message += (f"; {len(self.quarantined)} sample(s) were "
                        f"quarantined first: {self.quarantined}")
        super().__init__(message)


class _WorkerFailure:
    """A worker-side exception in transit to the consumer thread."""

    def __init__(self, exc, where):
        import traceback
        self.where = where
        self.exc = exc
        self.tb = traceback.format_exc()


_SKIPPED_BATCH = object()   # every sample of the batch was quarantined


def default_collate_fn(batch):
    """Stack samples into batch arrays (mirrors reference default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch, axis=0)
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch], axis=0)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn(list(items))
                            for items in zip(*batch))
    raise TypeError(f"cannot collate {type(sample)}")


def default_convert_fn(batch):
    return batch


def _to_device(batch, to_tensor=True):
    import jax.numpy as jnp
    if not to_tensor:
        return batch
    if isinstance(batch, np.ndarray):
        return Tensor(jnp.asarray(batch))
    if isinstance(batch, dict):
        return {k: _to_device(v) for k, v in batch.items()}
    if isinstance(batch, (list, tuple)):
        return type(batch)(_to_device(v) for v in batch)
    return batch


class DevicePrefetcher:
    """Double-buffered device-feed prefetch (docs/PERF.md).

    A background thread pulls host batches from ``source``, uploads them
    (``jax.device_put`` dispatches async) and keeps up to ``depth``
    device-resident batches ready, so the consumer's ``next()`` — i.e. the
    accelerator's feed — never waits on host batch assembly + transfer.
    The inline double-buffer in ``DataLoader.__iter__`` only overlaps the
    upload dispatch; this moves the whole host side (sample fetch,
    collate, conversion) off the consumer thread.

    Failure contract matches the self-healing DataLoader: a raising source
    ships its exception to the consumer (``DataLoaderWorkerError``), the
    done sentinel posts from a ``finally``, and every consumer wait is
    watchdog-bounded. Abandoning the iterator (break / GC) stops the
    thread promptly via the bounded hand-off.
    """

    def __init__(self, source, depth=2, timeout=None, convert=None):
        self.source = source
        self.depth = max(int(depth), 1)
        if timeout is None:
            timeout = float(os.environ.get('PADDLE_TPU_DATA_TIMEOUT', '')
                            or _DEFAULT_WATCHDOG_S)
        self.timeout = timeout
        self._convert = convert if convert is not None else _to_device

    def __iter__(self):
        out_q = queue.Queue(maxsize=self.depth)
        done = object()
        stop = threading.Event()

        def worker():
            try:
                for batch in self.source:
                    item = self._convert(batch)
                    while not stop.is_set():
                        try:
                            out_q.put(item, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:
                _post(_WorkerFailure(e, 'device prefetch'))
            finally:
                _post(done)

        def _post(item):
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        t = threading.Thread(target=worker, daemon=True,
                             name='paddle-tpu-device-prefetch')
        t.start()
        try:
            while True:
                batch = _watchdog.bounded_get(
                    out_q, timeout=self.timeout, alive=t.is_alive,
                    what='device prefetch batch')
                if batch is done:
                    return
                if isinstance(batch, _WorkerFailure):
                    raise DataLoaderWorkerError(
                        f"DataLoader device prefetch failed: "
                        f"{batch.exc!r}\n{batch.tb}")
                if _obs.enabled():
                    _obs.gauge('dataloader.prefetch_depth').set(out_q.qsize())
                yield batch
        finally:
            stop.set()
            # bounded join: the worker exits within one 0.1s put tick of
            # stop; a worker wedged inside _convert just times the join
            # out (False) rather than hanging generator teardown
            _watchdog.join_thread(t, timeout=2.0)


def _env_prefetch_depth():
    """PADDLE_TPU_PREFETCH: '' / '0' off, '1' -> depth 2, N -> depth N."""
    raw = os.environ.get('PADDLE_TPU_PREFETCH', '')
    try:
        n = int(raw or 0)
    except ValueError:
        return 0
    return 2 if n == 1 else max(n, 0)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0,
                 use_buffer_reader=True, use_shared_memory=True, timeout=0,
                 worker_init_fn=None, prefetch_factor=2,
                 persistent_workers=False, skip_bad_samples=None,
                 worker_max_restarts=None, prefetch_to_device=None):
        self.dataset = dataset
        self.return_list = return_list
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = max(int(num_workers), 0)
        self.worker_init_fn = worker_init_fn
        self.prefetch_factor = max(int(prefetch_factor), 1)
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        # fault-tolerance budgets (module docstring): watchdog wait, poison
        # quarantine, crashed-process-worker respawn. timeout=0 means
        # "unspecified" (env, then the 300s default); PADDLE_TPU_DATA_TIMEOUT=0
        # or a negative timeout= disables the deadline — consumer waits stay
        # liveness-probed but unbounded.
        if timeout:
            self.timeout = max(float(timeout), 0.0)
        else:
            self.timeout = float(
                os.environ.get('PADDLE_TPU_DATA_TIMEOUT', '')
                or _DEFAULT_WATCHDOG_S)
        if skip_bad_samples is None:
            skip_bad_samples = int(
                os.environ.get('PADDLE_TPU_DATA_SKIP_BUDGET', 0) or 0)
        self.skip_bad_samples = max(int(skip_bad_samples), 0)
        if worker_max_restarts is None:
            worker_max_restarts = int(
                os.environ.get('PADDLE_TPU_WORKER_RESTARTS', 2) or 0)
        self.worker_max_restarts = max(int(worker_max_restarts), 0)
        # device-feed prefetch (docs/PERF.md): None defers to
        # PADDLE_TPU_PREFETCH; an int is the prefetch depth (0 = off)
        if prefetch_to_device is None:
            self.prefetch_to_device = _env_prefetch_depth()
        elif prefetch_to_device is True:
            self.prefetch_to_device = 2
        else:
            self.prefetch_to_device = max(int(prefetch_to_device or 0), 0)
        self._quarantined = []       # (index, repr(exc)) of skipped samples
        self._q_lock = threading.Lock()
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    # -- poison-sample quarantine ------------------------------------------

    def quarantine_report(self):
        """(index, error) pairs for every sample skipped under the
        ``skip_bad_samples`` budget, in the order they were quarantined."""
        with self._q_lock:
            return list(self._quarantined)

    def _quarantine(self, index, exc):
        """Record one poisoned sample. True when the budget covered it;
        False when the budget is exhausted (caller must fail)."""
        with self._q_lock:
            if len(self._quarantined) >= self.skip_bad_samples:
                return False
            self._quarantined.append((index, repr(exc)))
        if _obs.enabled():
            _obs.counter('dataloader.quarantined').inc()
            _obs.event('quarantine', index=index, error=repr(exc))
        return True

    def _fetch_samples(self, indices):
        """dataset[i] for each index, quarantining poisoned samples within
        budget. Returns (samples, None) or (None, _WorkerFailure)."""
        samples = []
        for i in indices:
            try:
                samples.append(self.dataset[i])
            except Exception as e:
                if not self._quarantine(i, e):
                    return None, _WorkerFailure(
                        e, f"dataset[{i}] (skip budget "
                           f"{self.skip_bad_samples} exhausted)")
        return samples, None

    def _raw_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            batches = self.batch_sampler if self.batch_sampler is not None \
                else ([i] for i in range(len(self.dataset)))
            for indices in batches:
                samples, failure = self._fetch_samples(indices)
                if failure is not None:
                    raise DataLoaderWorkerError(
                        f"DataLoader failed in {failure.where}: "
                        f"{failure.exc!r}", self.quarantine_report()) \
                        from failure.exc
                if samples:     # skip a batch that was quarantined whole
                    yield self.collate_fn(samples)

    def _threaded_batches(self):
        """num_workers>0: worker threads build batches, main thread uploads.

        Failure contract: a worker that raises ships the exception to the
        consumer (re-raised as ``DataLoaderWorkerError``) and ALWAYS posts
        its done sentinel from a finally block — the silent-hang mode where
        a raising ``dataset[i]``/``collate_fn`` killed the thread and left
        the consumer blocked forever is structurally impossible. The
        consumer's queue wait is bounded (watchdog): dead workers are
        detected within a poll tick, hung workers within ``self.timeout``
        seconds."""
        if self._iterable_mode:
            yield from self._raw_batches()
            return
        indices_iter = iter(self.batch_sampler) if self.batch_sampler else \
            iter([[i] for i in range(len(self.dataset))])
        out_q = queue.Queue(maxsize=self.num_workers * self.prefetch_factor)
        lock = threading.Lock()
        seq = [0]
        pending = {}
        done = object()

        def worker(wid):
            try:
                if self.worker_init_fn:
                    self.worker_init_fn(wid)
                while True:
                    with lock:
                        try:
                            my_seq = seq[0]
                            indices = next(indices_iter)
                            seq[0] += 1
                        except StopIteration:
                            return
                    samples, failure = self._fetch_samples(indices)
                    if failure is not None:
                        out_q.put((my_seq, failure))
                        return
                    if not samples:     # whole batch quarantined
                        out_q.put((my_seq, _SKIPPED_BATCH))
                        continue
                    try:
                        batch = self.collate_fn(samples)
                    except Exception as e:
                        out_q.put((my_seq, _WorkerFailure(e, 'collate_fn')))
                        return
                    out_q.put((my_seq, batch))
            except BaseException as e:   # worker_init_fn, sampler, ...
                out_q.put((None, _WorkerFailure(e, 'worker')))
            finally:
                # the sentinel is unconditional: the consumer must never
                # wait on a thread that already died
                out_q.put((None, done))

        threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for t in threads:
            t.start()

        def workers_alive():
            return any(t.is_alive() for t in threads)

        finished = 0
        next_seq = 0
        while finished < self.num_workers:
            if _obs.enabled():
                _obs.gauge('dataloader.queue_depth').set(out_q.qsize())
            try:
                s, batch = _watchdog.bounded_get(
                    out_q, timeout=self.timeout, alive=workers_alive,
                    what='DataLoader batch')
            except _watchdog.WatchdogTimeout as e:
                if _obs.enabled():
                    _obs.counter('dataloader.watchdog_timeouts').inc()
                    _obs.event('dataloader_watchdog', error=str(e))
                raise DataLoaderWorkerError(
                    f"DataLoader wedged: {e}", self.quarantine_report()) \
                    from e
            if batch is done:
                finished += 1
                continue
            if isinstance(batch, _WorkerFailure):
                raise DataLoaderWorkerError(
                    f"DataLoader worker failed in {batch.where}: "
                    f"{batch.exc!r}\n{batch.tb}", self.quarantine_report())
            pending[s] = batch
            while next_seq in pending:
                b = pending.pop(next_seq)
                next_seq += 1
                if b is not _SKIPPED_BATCH:
                    yield b
        while next_seq in pending:
            b = pending.pop(next_seq)
            next_seq += 1
            if b is not _SKIPPED_BATCH:
                yield b

    def _process_batches(self):
        """num_workers>0 + shared memory: fork()ed worker processes collate
        batches into the native shm prefetch ring (csrc/prefetch.cpp) — no
        pickling of array payloads. Falls back to the threaded path when the
        native lib is unavailable or batches are not plain ndarray tuples.

        The pool self-heals: crashed workers are respawned (up to
        ``worker_max_restarts``) with their in-flight batch requeued,
        poisoned samples are quarantined through the shared budget, and a
        stall past the watchdog budget raises instead of hanging."""
        from .._native.process_pool import ProcessWorkerPool
        indices = list(self.batch_sampler) if self.batch_sampler is not None \
            else [[i] for i in range(len(self.dataset))]
        pool = ProcessWorkerPool(self.dataset, indices, self.collate_fn,
                                 self.num_workers,
                                 capacity=self.num_workers *
                                 self.prefetch_factor,
                                 worker_init_fn=self.worker_init_fn,
                                 max_restarts=self.worker_max_restarts,
                                 watchdog_timeout=self.timeout,
                                 quarantine=self._quarantine)
        yield from pool

    def _shm_compatible(self):
        """Process+shm transport handles flat tuples of numeric ndarrays
        (the hot path); dicts/strings/objects use the threaded path."""
        try:
            if self.batch_sampler is not None:
                it = iter(self.batch_sampler)
                first = next(it, None)
                if it is self.batch_sampler and first is not None:
                    # one-shot sampler (generator): the probe consumed its
                    # first batch — stitch it back so iteration sees it
                    import itertools
                    self.batch_sampler = itertools.chain([first], it)
            else:
                first = [0] if len(self.dataset) else None
            if first is None:
                return False
            batch = self.collate_fn([self.dataset[i] for i in first[:1]])
            items = batch if isinstance(batch, (list, tuple)) else [batch]
            import numpy as _np
            for a in items:
                a = _np.asarray(a)
                if a.dtype == object or a.dtype.kind in 'USV':
                    return False
            return True
        except Exception:
            return False

    def _parallel_batches(self):
        if self._iterable_mode or not self.use_shared_memory:
            return self._threaded_batches()
        try:
            from .._native import available as _native_ok
            import multiprocessing as mp
            if (_native_ok() and 'fork' in mp.get_all_start_methods()
                    and self._shm_compatible()):
                return self._process_batches()
        except Exception:
            pass
        return self._threaded_batches()

    # a single batch wait above this lands a streamed `input_stall` event
    # (the anomaly doctor's input-bound corroboration; the histogram alone
    # only shows up at snapshot time)
    _STALL_EVENT_MS = 1000.0

    def _timed(self, source):
        """Telemetry wrapper: how long the consumer waits for each host
        batch (assembly + collate stall the device would see)."""
        it = iter(source)
        while True:
            sw = _obs.Stopwatch()
            try:
                b = next(it)
            except StopIteration:
                return
            if _obs.enabled():
                wait_ms = sw.elapsed_ms()
                _obs.histogram('dataloader.next_wait_ms').observe(wait_ms)
                _obs.counter('dataloader.batches').inc()
                if wait_ms >= self._STALL_EVENT_MS:
                    _obs.counter('dataloader.stalls').inc()
                    _obs.event('input_stall', wait_ms=round(wait_ms, 1))
            yield b

    def __iter__(self):
        source = self._parallel_batches() if self.num_workers > 0 else \
            self._raw_batches()
        if self.prefetch_to_device:
            # background device-feed prefetch: the whole host side (sample
            # fetch + collate + upload) runs ahead of the consumer; _timed
            # wraps the OUTSIDE so dataloader.next_wait_ms measures the
            # wait the accelerator would actually see
            prefetched = DevicePrefetcher(source,
                                          depth=self.prefetch_to_device,
                                          timeout=self.timeout)
            if _obs.enabled():
                prefetched = self._timed(prefetched)
            yield from prefetched
            return
        if _obs.enabled():
            source = self._timed(source)
        if not self.use_buffer_reader:
            for b in source:
                yield _to_device(b)
            return
        # double-buffer: upload batch N+1 while N is being consumed
        it = iter(source)
        try:
            nxt = _to_device(next(it))
        except StopIteration:
            return
        for b in it:
            cur, nxt = nxt, _to_device(b)  # device_put dispatches async
            yield cur
        yield nxt

    @staticmethod
    def from_generator(feed_list=None, capacity=4, use_double_buffer=True,
                       iterable=True, return_list=True, use_multiprocess=False,
                       drop_last=True):
        """fluid-era generator loader."""
        return _GeneratorLoader(capacity, drop_last)

    @staticmethod
    def from_dataset(dataset, places=None, drop_last=True):
        return DataLoader(dataset, drop_last=drop_last)


class _GeneratorLoader:
    def __init__(self, capacity, drop_last):
        self._gen = None
        self.capacity = capacity

    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        from ..batch import batch as batch_reader
        self._gen = lambda: (default_collate_fn(b)
                             for b in batch_reader(reader, batch_size,
                                                   drop_last)())
        return self

    def set_sample_list_generator(self, reader, places=None):
        self._gen = lambda: (default_collate_fn(b) for b in reader())
        return self

    def set_batch_generator(self, reader, places=None):
        self._gen = lambda: iter(reader())
        return self

    def __iter__(self):
        for b in self._gen():
            yield _to_device(b)

    def __call__(self):
        return iter(self)
