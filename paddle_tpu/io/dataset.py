"""Datasets. Parity: python/paddle/fluid/dataloader/dataset.py."""
import bisect

import numpy as np

__all__ = ['Dataset', 'IterableDataset', 'TensorDataset', 'ComposeDataset',
           'ChainDataset', 'ConcatDataset', 'Subset', 'random_split']


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError(
            "'{}' not implement __getitem__".format(type(self).__name__))

    def __len__(self):
        raise NotImplementedError(
            "'{}' not implement __len__".format(type(self).__name__))


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError(
            "'{}' not implement __iter__".format(type(self).__name__))

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lens = {len(t) for t in tensors}
        assert len(lens) == 1, "tensors must share dim 0"
        self.tensors = tensors

    def __getitem__(self, index):
        return tuple(t[index] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        lens = {len(d) for d in self.datasets}
        assert len(lens) == 1

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, idx):
        sample = []
        for d in self.datasets:
            item = d[idx]
            sample.extend(item if isinstance(item, (list, tuple)) else [item])
        return tuple(sample)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        start = 0 if ds_idx == 0 else self.cumulative_sizes[ds_idx - 1]
        return self.datasets[ds_idx][idx - start]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if sum(lengths) != total:
        raise ValueError("sum of lengths != dataset size")
    perm = np.random.permutation(total)
    out, off = [], 0
    for n in lengths:
        out.append(Subset(dataset, perm[off:off + n].tolist()))
        off += n
    return out
