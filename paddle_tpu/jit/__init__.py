"""paddle_tpu.jit: to_static + save/load.

Parity: python/paddle/fluid/dygraph/jit.py (@declarative/to_static,
jit.save/jit.load, TranslatedLayer). TPU-first redesign: to_static wraps the
Python function with jax.jit — the whole forward (and backward, when traced
through a grad) becomes ONE XLA computation; no ProgramTranslator AST pass is
needed because tracing handles Python control flow on static shapes, and
lax.cond/while are exposed for data-dependent control flow.
"""
import functools
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter, apply_op
from ..core import rng as _rng
from ..core import autograd
from ..nn.layer_base import Layer

__all__ = ['to_static', 'declarative', 'save', 'load', 'TranslatedLayer',
           'not_to_static', 'ignore_module', 'enable_to_static', 'InputSpec']

_jit_enabled = [True]


def enable_to_static(flag):
    _jit_enabled[0] = bool(flag)


def _extract_tensors(obj):
    """Flatten (args, kwargs) pytree, pulling out Tensors."""
    tensors = []

    def rec(o):
        if isinstance(o, Tensor):
            tensors.append(o)
            return ('T', len(tensors) - 1)
        if isinstance(o, list):
            return ('L', [rec(v) for v in o])
        if isinstance(o, tuple):
            return ('U', [rec(v) for v in o])
        if isinstance(o, dict):
            return ('D', {k: rec(v) for k, v in o.items()})
        return ('C', o)

    tree = rec(obj)

    def rebuild(tensor_list):
        def rr(node):
            tag, val = node
            if tag == 'T':
                return tensor_list[val]
            if tag == 'L':
                return [rr(v) for v in val]
            if tag == 'U':
                return tuple(rr(v) for v in val)
            if tag == 'D':
                return {k: rr(v) for k, v in val.items()}
            return val
        return rr(tree)

    return tensors, rebuild


class StaticFunction:
    """Compiled wrapper around a Tensor-level python function.

    The whole call compiles to one cached XLA computation. Gradients flow:
    the compiled call is ONE tape node whose vjp re-traces the same pure
    function under jax.vjp (XLA caches that too). Model parameters are
    implicit differentiable inputs.
    """

    def __init__(self, fn, input_spec=None, instance=None):
        self._fn = fn
        self._instance = instance
        self._input_spec = input_spec
        self._struct = None
        self._n_out = None
        self._jitted = None

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = StaticFunction(self._fn, self._input_spec, instance)
        return bound

    @property
    def __name__(self):
        return getattr(self._fn, '__name__', 'static_fn')

    def _pure(self, rebuild, params, n_data, key, training):
        fn, instance = self._fn, self._instance
        sf = self

        def pure(*vals):
            data_vals = vals[:n_data]
            param_vals = vals[n_data:]
            originals = [p._value for p in params]
            for p, v in zip(params, param_vals):
                p._value = v
            try:
                from ..core.rng import key_scope
                with key_scope(key):
                    args2, kwargs2 = rebuild([Tensor(v) for v in data_vals])
                    with autograd.no_grad():
                        if instance is not None:
                            out = fn(instance, *args2, **kwargs2)
                        else:
                            out = fn(*args2, **kwargs2)
            finally:
                for p, v in zip(params, originals):
                    p._value = v
            flat, tree = _flatten_out(out)
            sf._struct = tree
            return tuple(t._value for t in flat)
        return pure

    def __call__(self, *args, **kwargs):
        if not _jit_enabled[0]:
            if self._instance is not None:
                return self._fn(self._instance, *args, **kwargs)
            return self._fn(*args, **kwargs)

        tensors, rebuild = _extract_tensors((list(args), dict(kwargs)))
        rebuild_ak = lambda ts: rebuild(ts)
        if self._instance is not None and isinstance(self._instance, Layer):
            params = [p for p in self._instance.parameters() if p.trainable]
        else:
            params = []
        n_data = len(tensors)
        key = _rng.next_key()
        training = getattr(self._instance, 'training', True)

        def rebuild2(ts):
            a, k = rebuild_ak(ts)
            return a, k

        pure = self._pure(rebuild2, params, n_data, key, training)
        all_inputs = tuple(tensors) + tuple(params)

        if self._struct is None:
            # first call: run the pure fn eagerly once to learn the output
            # structure, then compile.
            out_vals = pure(*[t._value for t in all_inputs])
            self._n_out = len(out_vals)
            self._jitted = jax.jit(pure)
            if self._n_out == 1:
                out = apply_op(lambda *v: pure(*v)[0], all_inputs)
                return _unflatten_out([out], self._struct)
            outs = apply_op(pure, all_inputs, n_outputs=self._n_out)
            return _unflatten_out(list(outs), self._struct)

        jitted = self._jitted
        if self._n_out == 1:
            out = apply_op(lambda *v: jitted(*v)[0], all_inputs)
            return _unflatten_out([out], self._struct)
        outs = apply_op(lambda *v: jitted(*v), all_inputs,
                        n_outputs=self._n_out)
        return _unflatten_out(list(outs), self._struct)


def _flatten_out(out):
    flat = []

    def rec(obj):
        if isinstance(obj, Tensor):
            flat.append(obj)
            return ('T', len(flat) - 1)
        if isinstance(obj, list):
            return ('L', [rec(o) for o in obj])
        if isinstance(obj, tuple):
            return ('U', [rec(o) for o in obj])
        if isinstance(obj, dict):
            return ('D', {k: rec(v) for k, v in obj.items()})
        return ('C', obj)
    tree = rec(out)
    return flat, tree


def _unflatten_out(tensors, tree):
    def rr(node):
        tag, val = node
        if tag == 'T':
            return tensors[val]
        if tag == 'L':
            return [rr(v) for v in val]
        if tag == 'U':
            return tuple(rr(v) for v in val)
        if tag == 'D':
            return {k: rr(v) for k, v in val.items()}
        return val
    return rr(tree)


def to_static(function=None, input_spec=None, build_strategy=None, **kwargs):
    """Decorator: compile a dygraph function/method into one XLA computation."""
    def deco(fn):
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(type(layer).forward, input_spec, layer)
            object.__setattr__(layer, 'forward', sf)
            return layer
        return StaticFunction(fn, input_spec)
    if function is not None:
        return deco(function)
    return deco


declarative = to_static


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


def save(layer, path, input_spec=None, **configs):
    """jit.save: params + meta (+ StableHLO export when input_spec given).

    Parity: fluid/dygraph/jit.py:save -> __model__ + params files.
    """
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    from ..framework import save as fsave
    state = layer.state_dict() if isinstance(layer, Layer) else {}
    fsave(state, path + '.pdparams')
    meta = {'class': type(layer).__name__}
    if input_spec is not None:
        try:
            def fwd(*vals):
                with autograd.no_grad():
                    out = layer(*[Tensor(v) for v in vals])
                return out._value if isinstance(out, Tensor) else out
            shapes = [jax.ShapeDtypeStruct(tuple(abs(d) for d in s.shape),
                                           s.dtype) for s in input_spec]
            lowered = jax.jit(fwd).lower(*shapes)
            meta['stablehlo'] = lowered.as_text()
            meta['input_shapes'] = [list(s.shape) for s in input_spec]
            meta['input_dtypes'] = [str(np.dtype(s.dtype)) for s in input_spec]
        except Exception as e:  # export is best-effort
            meta['export_error'] = str(e)
    with open(path + '.pdmodel', 'wb') as f:
        pickle.dump(meta, f)


def load(path, **configs):
    from ..framework import load as fload
    state = fload(path + '.pdparams')
    meta = {}
    if os.path.exists(path + '.pdmodel'):
        with open(path + '.pdmodel', 'rb') as f:
            meta = pickle.load(f)
    return TranslatedLayer(state, meta)


class TranslatedLayer(Layer):
    """Reloaded model: holds the saved state dict (+ exported HLO text)."""

    def __init__(self, state, meta):
        super().__init__()
        self._state = state
        self._meta = meta
        for k, v in state.items():
            safe = k.replace('.', '_')
            if isinstance(v, Parameter):
                self.add_parameter(safe, v)
            elif isinstance(v, Tensor):
                self.register_buffer(safe, v)

    def program(self):
        return self._meta.get('stablehlo')

    def state_dict(self, *a, **k):
        return dict(self._state)

    def forward(self, *args, **kwargs):
        raise RuntimeError(
            "TranslatedLayer from jit.load carries weights + exported HLO; "
            "rebuild the model class and set_state_dict(layer.state_dict()) "
            "to run it (executable reload is a planned feature).")


class InputSpec:
    """Parity: paddle.static.InputSpec."""

    def __init__(self, shape, dtype='float32', name=None):
        from ..core.dtypes import convert_dtype
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)
