"""paddle_tpu.jit: to_static + save/load.

Parity: python/paddle/fluid/dygraph/jit.py (@declarative/to_static,
jit.save/jit.load, TranslatedLayer). TPU-first redesign: to_static wraps the
Python function with jax.jit — the whole forward (and backward, when traced
through a grad) becomes ONE XLA computation; no ProgramTranslator AST pass is
needed because tracing handles Python control flow on static shapes, and
lax.cond/while are exposed for data-dependent control flow.
"""
import functools
import hashlib
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter, apply_op
from ..core import rng as _rng
from ..core import autograd
from ..nn.layer_base import Layer

__all__ = ['to_static', 'declarative', 'save', 'load', 'TranslatedLayer',
           'not_to_static', 'ignore_module', 'enable_to_static', 'InputSpec']

_jit_enabled = [True]


def enable_to_static(flag):
    _jit_enabled[0] = bool(flag)


def _extract_tensors(obj):
    """Flatten (args, kwargs) pytree, pulling out Tensors."""
    tensors = []

    def rec(o):
        if isinstance(o, Tensor):
            tensors.append(o)
            return ('T', len(tensors) - 1)
        if isinstance(o, np.ndarray):
            # ndarray args become traced Tensor inputs (same conversion the
            # reference applies to to_static inputs): keeps array data out
            # of the cache key and the compiled constant pool. Host-side
            # numpy use of such an arg inside the fn is unsupported under
            # tracing — pass a hashable scalar/tuple instead.
            tensors.append(Tensor(jnp.asarray(o)))
            return ('T', len(tensors) - 1)
        if isinstance(o, list):
            return ('L', [rec(v) for v in o])
        if isinstance(o, tuple):
            return ('U', [rec(v) for v in o])
        if isinstance(o, dict):
            # sorted: extraction order must agree with _tree_sig's sorted
            # key order, or two kwarg orderings would share a cache entry
            # while binding tensors to different slots
            return ('D', {k: rec(o[k]) for k in sorted(o)})
        return ('C', o)

    tree = rec(obj)

    def rebuild(tensor_list):
        def rr(node):
            tag, val = node
            if tag == 'T':
                return tensor_list[val]
            if tag == 'L':
                return [rr(v) for v in val]
            if tag == 'U':
                return tuple(rr(v) for v in val)
            if tag == 'D':
                return {k: rr(v) for k, v in val.items()}
            return val
        return rr(tree)

    return tensors, rebuild


class StaticFunction:
    """Compiled wrapper around a Tensor-level python function.

    The whole call compiles to ONE cached XLA computation (per training flag +
    argument structure). Design (TPU-first; replaces the reference's
    ProgramTranslator AST pass, fluid/dygraph/jit.py):

    - discovery pass: the fn runs eagerly once under a capture watch; every
      pre-existing Tensor it reads (closure parameters, buffers, constants)
      is recorded and becomes an explicit input of the compiled function, so
      optimizer updates are picked up and gradients flow to parameters even
      when they are captured by closure rather than passed as arguments.
    - mutated captures (e.g. BatchNorm running stats) become extra OUTPUTS of
      the pure function and are written back after each call — no tracer ever
      leaks into live state.
    - gradients: the compiled call is one tape node whose vjp re-traces the
      same pure function under jax.vjp (XLA caches that too).
    """

    def __init__(self, fn, input_spec=None, instance=None):
        self._fn = fn
        self._instance = instance
        self._input_spec = input_spec
        self._layers = []         # union of Layers touched (mode cache keys)
        self._layer_ids = set()
        self._cache = {}          # (training, tree_sig) -> [mode variants]

    def __get__(self, instance, owner):
        if instance is None:
            return self
        cached = getattr(instance, '_jit_cache', None)
        if cached is None:
            cached = {}
            object.__setattr__(instance, '_jit_cache', cached)
        me = cached.get(id(self))
        if me is None:
            me = StaticFunction(self._fn, self._input_spec, instance)
            cached[id(self)] = me
        return me

    @property
    def __name__(self):
        return getattr(self._fn, '__name__', 'static_fn')

    def _call_fn(self, args2, kwargs2):
        if self._instance is not None:
            return self._fn(self._instance, *args2, **kwargs2)
        return self._fn(*args2, **kwargs2)

    def _discover(self, tensors, rebuild, entry):
        """Eager run under a capture watch: find closure tensors + mutations.

        Runs once per cache entry (per training-mode combination + argument
        structure) — the set of touched tensors and which of them the fn
        mutates is mode-dependent (e.g. BatchNorm running stats update only
        in train mode).
        """
        from ..core import tensor as tensor_mod
        clones = [Tensor(t._value) for t in tensors]
        watch = tensor_mod._CaptureWatch()
        for c in clones:
            watch.produced.add(id(c))
        key = _rng.next_key()
        prev = tensor_mod.set_capture_watch(watch)
        try:
            with _rng.key_scope(key), autograd.no_grad():
                args2, kwargs2 = rebuild(clones)
                self._call_fn(args2, kwargs2)
        finally:
            tensor_mod.set_capture_watch(prev)
        mutated = []
        for i, (t, v) in enumerate(zip(watch.captured, watch.captured_vals)):
            if t._value is not v:
                mutated.append(i)
                t._value = v  # undo the eager side effect; replayed compiled
        entry['captured'] = list(watch.captured)
        entry['mutated_idx'] = mutated
        for l in watch.layers:
            if id(l) not in self._layer_ids:
                self._layer_ids.add(id(l))
                self._layers.append(l)

    def _make_pure(self, rebuild, n_data, entry):
        fn_call = self._call_fn
        ext, mutated = entry['captured'], entry['mutated_idx']

        def pure(key, *vals):
            data_vals = vals[:n_data]
            ext_vals = vals[n_data:]
            originals = [p._value for p in ext]
            for p, v in zip(ext, ext_vals):
                p._value = v
            try:
                with _rng.key_scope(key), autograd.no_grad():
                    args2, kwargs2 = rebuild([Tensor(v) for v in data_vals])
                    out = fn_call(args2, kwargs2)
                state_out = tuple(ext[i]._value for i in mutated)
            finally:
                for p, v in zip(ext, originals):
                    p._value = v
            flat, tree = _flatten_out(out)
            entry['struct'] = tree
            entry['n_user_out'] = len(flat)
            return tuple(t._value for t in flat) + state_out
        return pure

    def __call__(self, *args, **kwargs):
        if not _jit_enabled[0]:
            return self._call_fn(args, dict(kwargs))

        tensors, rebuild = _extract_tensors((list(args), dict(kwargs)))

        training = bool(getattr(self._instance, 'training', True))
        sig = (training, _tree_sig((list(args), dict(kwargs))))
        # each signature holds mode VARIANTS: a variant compiled when the
        # layer list had n_layers entries depends only on those layers'
        # train/eval flags, so it stays reachable even after later discovery
        # appends new layers (prefix match, not whole-list match)
        variants = self._cache.setdefault(sig, [])
        entry = None
        for v in variants:
            modes_now = tuple(bool(l.training)
                              for l in self._layers[:v['n_layers']])
            if modes_now == v['modes']:
                entry = v
                break
        if entry is None:
            entry = {'struct': None, 'n_user_out': None}
            self._discover(tensors, rebuild, entry)
            entry['n_layers'] = len(self._layers)
            entry['modes'] = tuple(bool(l.training) for l in self._layers)
            entry['jitted'] = jax.jit(
                self._make_pure(rebuild, len(tensors), entry))
            variants.append(entry)

        key = _rng.next_key()
        jitted = entry['jitted']
        captured, mutated_idx = entry['captured'], entry['mutated_idx']
        all_inputs = (Tensor(key),) + tuple(tensors) + tuple(captured)

        if entry['struct'] is None:
            # learn output structure via one abstract trace (also warms jit)
            jax.eval_shape(
                jitted, *[jax.ShapeDtypeStruct(tuple(t._value.shape),
                                               t._value.dtype)
                          for t in all_inputs])

        n_user = entry['n_user_out']
        n_total = n_user + len(mutated_idx)
        if n_total == 1:
            outs = (apply_op(lambda *v: jitted(*v)[0], all_inputs),)
        else:
            outs = apply_op(lambda *v: jitted(*v), all_inputs,
                            n_outputs=n_total)
        # write back mutated buffers (running stats etc.) eagerly;
        # _inplace_value clears any stale tape node and notifies an outer
        # discovery watch (nested to_static)
        with autograd.no_grad():
            for i, idx in enumerate(mutated_idx):
                captured[idx]._inplace_value(outs[n_user + i]._value)
        return _unflatten_out(list(outs[:n_user]), entry['struct'])


def _tree_sig(obj):
    """Hashable signature of the (args, kwargs) structure: tensors abstracted
    to shape/dtype markers, constants kept (they get baked into the trace)."""
    if isinstance(obj, Tensor):
        return ('T', tuple(obj._value.shape), str(obj._value.dtype))
    if isinstance(obj, list):
        return ('L',) + tuple(_tree_sig(v) for v in obj)
    if isinstance(obj, tuple):
        return ('U',) + tuple(_tree_sig(v) for v in obj)
    if isinstance(obj, dict):
        return ('D',) + tuple(sorted((k, _tree_sig(v)) for k, v in obj.items()))
    if isinstance(obj, np.ndarray):
        # arrays are lifted to traced inputs by _extract_tensors — only the
        # shape/dtype matter for the compiled cache
        return ('T', tuple(obj.shape), str(obj.dtype))
    try:
        hash(obj)
        return ('C', type(obj).__qualname__, obj)
    except TypeError:
        # unhashable constant gets baked into the trace: key by VALUE, not
        # repr (repr truncation would collide two different payloads)
        try:
            payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:
            raise TypeError(
                f"to_static argument of type {type(obj).__name__} is "
                f"neither a Tensor/ndarray nor hashable/picklable; pass it "
                f"as a Tensor or a hashable constant ({e})") from e
        return ('C', type(obj).__qualname__,
                hashlib.sha1(payload).hexdigest())


def _flatten_out(out):
    flat = []

    def rec(obj):
        if isinstance(obj, Tensor):
            flat.append(obj)
            return ('T', len(flat) - 1)
        if isinstance(obj, list):
            return ('L', [rec(o) for o in obj])
        if isinstance(obj, tuple):
            return ('U', [rec(o) for o in obj])
        if isinstance(obj, dict):
            return ('D', {k: rec(v) for k, v in obj.items()})
        return ('C', obj)
    tree = rec(out)
    return flat, tree


def _unflatten_out(tensors, tree):
    def rr(node):
        tag, val = node
        if tag == 'T':
            return tensors[val]
        if tag == 'L':
            return [rr(v) for v in val]
        if tag == 'U':
            return tuple(rr(v) for v in val)
        if tag == 'D':
            return {k: rr(v) for k, v in val.items()}
        return val
    return rr(tree)


def to_static(function=None, input_spec=None, build_strategy=None, **kwargs):
    """Decorator: compile a dygraph function/method into one XLA computation."""
    def deco(fn):
        if isinstance(fn, Layer):
            layer = fn
            sf = StaticFunction(type(layer).forward, input_spec, layer)
            object.__setattr__(layer, 'forward', sf)
            return layer
        return StaticFunction(fn, input_spec)
    if function is not None:
        return deco(function)
    return deco


declarative = to_static


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


def save(layer, path, input_spec=None, **configs):
    """jit.save: params + meta (+ StableHLO export when input_spec given).

    Parity: fluid/dygraph/jit.py:save -> __model__ + params files.
    """
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    from ..framework import save as fsave
    state = layer.state_dict() if isinstance(layer, Layer) else {}
    fsave(state, path + '.pdparams')
    meta = {'class': type(layer).__name__}
    if input_spec is not None:
        try:
            # jax.export is a lazy submodule: a bare `import jax` does NOT
            # bind the attribute, so the export machinery must be imported
            # explicitly or every save silently degrades to export_error
            import jax.export  # noqa: F401
            # portable jax.export with the layer state as ARGUMENTS (not
            # baked constants) so TranslatedLayer.forward can run the
            # executable against its reloaded .pdparams in a fresh process
            # (parity: fluid/dygraph/io.py:546 TranslatedLayer runs the
            # loaded program)
            from ..nn.layer_base import functional_call
            # exported state == exactly what .pdparams stores
            # (state_dict(): params + PERSISTABLE buffers — exporting a
            # non-persistable buffer would KeyError at reload)
            all_state = {k: (v._value if isinstance(v, Tensor) else
                             jnp.asarray(np.asarray(v)))
                         for k, v in layer.state_dict().items()}
            state_names = sorted(all_state)

            def fwd(state_vals, *ins):
                st = dict(zip(state_names, state_vals))
                with autograd.no_grad():
                    out, _ = functional_call(
                        layer, st, *[Tensor(v) for v in ins])
                if isinstance(out, (tuple, list)):
                    return tuple(o._value if isinstance(o, Tensor) else o
                                 for o in out)
                return out._value if isinstance(out, Tensor) else out

            scope = jax.export.SymbolicScope()
            in_specs = []
            for i, s in enumerate(input_spec):
                dims = []
                for j, d in enumerate(s.shape):
                    if d is None or int(d) < 0:
                        # dim 0 shares one batch symbol across inputs
                        dims.append('batch' if j == 0
                                    else 'b%d_%d' % (i, j))
                    else:
                        dims.append(str(d))
                shape = jax.export.symbolic_shape(','.join(dims),
                                                  scope=scope)
                in_specs.append(jax.ShapeDtypeStruct(shape, s.dtype))
            state_specs = [
                jax.ShapeDtypeStruct(tuple(np.shape(all_state[n])),
                                     all_state[n].dtype)
                for n in state_names]
            exported = jax.export.export(jax.jit(fwd))(state_specs,
                                                       *in_specs)
            meta['exported'] = {'blob': bytes(exported.serialize()),
                                'state_names': state_names}
            meta['stablehlo'] = exported.mlir_module()
            meta['input_shapes'] = [list(s.shape) for s in input_spec]
            meta['input_dtypes'] = [str(np.dtype(s.dtype))
                                    for s in input_spec]
        except Exception as e:  # export is best-effort
            meta['export_error'] = str(e)
    from ..resilience.atomic_io import atomic_pickle_dump
    atomic_pickle_dump(meta, path + '.pdmodel')


def load(path, **configs):
    from ..framework import load as fload
    state = fload(path + '.pdparams')
    meta = {}
    if os.path.exists(path + '.pdmodel'):
        with open(path + '.pdmodel', 'rb') as f:
            meta = pickle.load(f)
    return TranslatedLayer(state, meta)


class TranslatedLayer(Layer):
    """Reloaded model: holds the saved state dict (+ exported HLO text)."""

    def __init__(self, state, meta):
        super().__init__()
        self._state = state
        self._meta = meta
        for k, v in state.items():
            safe = k.replace('.', '_')
            if isinstance(v, Parameter):
                self.add_parameter(safe, v)
            elif isinstance(v, Tensor):
                self.register_buffer(safe, v)

    def program(self):
        return self._meta.get('stablehlo')

    def state_dict(self, *a, **k):
        return dict(self._state)

    def forward(self, *args, **kwargs):
        exported = self._meta.get('exported')
        if exported is None:
            raise RuntimeError(
                "TranslatedLayer: this model was saved without input_spec "
                "(export error: %s) — re-save with jit.save(layer, path, "
                "input_spec=[...]) to get a runnable reload, or rebuild "
                "the model class and set_state_dict()."
                % self._meta.get('export_error', 'none recorded'))
        if getattr(self, '_exec', None) is None:
            import jax.export  # noqa: F401 — lazy submodule (see save())
            self._exec = jax.export.deserialize(bytearray(exported['blob']))
        state_vals = []
        for n in exported['state_names']:
            v = self._state[n]
            state_vals.append(v._value if isinstance(v, Tensor)
                              else jnp.asarray(np.asarray(v)))
        in_dtypes = [np.dtype(d) for d in
                     self._meta.get('input_dtypes',
                                    ['float32'] * len(args))]
        vals = [a._value if isinstance(a, Tensor)
                else jnp.asarray(np.asarray(a, dt))
                for a, dt in zip(args, in_dtypes)]
        out = self._exec.call(state_vals, *vals)
        if isinstance(out, (tuple, list)):
            outs = type(out)(Tensor(o) for o in out)
            return outs[0] if len(outs) == 1 else outs
        return Tensor(out)


class InputSpec:
    """Parity: paddle.static.InputSpec."""

    def __init__(self, shape, dtype='float32', name=None):
        from ..core.dtypes import convert_dtype
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)


# -- 2.0-beta jit namespace tail ---------------------------------------------
from ..fluid.dygraph import TracedLayer  # noqa: F401,E402
from ..fluid.dygraph import set_code_level, set_verbosity  # noqa: F401,E402


class ProgramTranslator:
    """Dygraph->static translator controller (jit ProgramTranslator).
    Tracing is jax-side here; the enable flag gates to_static's jit."""

    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def __init__(self):
        self.enable_to_static = True

    def enable(self, enable_to_static=True):
        self.enable_to_static = bool(enable_to_static)

    def get_output(self, dygraph_func, *args, **kwargs):
        return to_static(dygraph_func)(*args, **kwargs)

    def get_func(self, dygraph_func):
        return to_static(dygraph_func)

    def get_program(self, dygraph_func, *args, **kwargs):
        raise RuntimeError(
            "ProgramTranslator.get_program: the TPU rebuild lowers traced "
            "functions straight to XLA (no ProgramDesc); use "
            "get_func/get_output, or static.Program capture for a Program")

    def get_code(self, dygraph_func):
        import inspect
        return inspect.getsource(dygraph_func)
