"""Pallas TPU kernels for hot ops.

Implemented here (each with interpret-mode CPU tests):
- flash_attention: forward + backward kernels, causal/non-causal, key-padding
  bias, in-kernel PRNG attention dropout (kernels/flash_attention.py);
- fused layer norm / rms norm forward kernels with closed-form backward
  (kernels/fused_norm.py).

These replace the reference's hand-written CUDA/cuDNN kernels
(paddle/fluid/operators/fused/*attention*, layer_norm_op.cu) with TPU-native
Pallas implementations.
"""
from .flash_attention import flash_attention_bhld  # noqa: F401
from .fused_norm import fused_layer_norm, fused_rms_norm  # noqa: F401
