"""Pallas TPU kernels for hot ops (flash attention, fused norms).

These replace the reference's hand-written CUDA/cuDNN kernels
(paddle/fluid/operators/*.cu) with TPU-native Pallas implementations.
"""
