"""Shared Pallas kernel utilities (single source for PRNG masks + tiling)."""
import jax
import jax.numpy as jnp

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False


def tile_keep_scale(seed_ref, tile_id, shape, dropout_p):
    """Regenerate a dropout keep/(1-p) mask for one tile from the TPU
    hardware PRNG. Deterministic in (seed, tile_id), so forward and backward
    kernels rebuild the identical mask without ever storing it. Mosaic caps
    prng_seed at 2 values, so callers pre-fold coordinates into tile_id."""
    pltpu.prng_seed(seed_ref[0, 0], tile_id)
    bits = pltpu.prng_random_bits(shape)
    u = jax.lax.bitcast_convert_type(bits, jnp.uint32)
    thresh = jnp.uint32(min(int(dropout_p * 4294967296.0), 4294967295))
    keep = u >= thresh
    return keep.astype(jnp.float32) / (1.0 - dropout_p)


def row_block(n):
    """Largest row-tile size dividing n. Returns None when n has no multiple-
    of-8 tiling (Mosaic requires the sublane dim divisible by 8) — callers
    must fall back to the XLA path."""
    for bn in (256, 128, 64, 32, 16, 8):
        if n % bn == 0:
            return bn
    return None
