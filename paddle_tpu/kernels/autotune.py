"""On-hardware autotuning for the flash-attention dispatch.

The right (block_q, block_k) tiling — and whether the Pallas kernel beats
XLA's fused attention at all — depends on sequence length, head dim,
batch and the mask/dropout mix; fixed constants leave performance on the
table (the round-2 kernel shipped block 512x512 everywhere). This module
times candidates ON THE REAL CHIP once per shape signature:

- ``autotune_attention(...)`` builds a training-shaped step (forward +
  backward, the bench workload) per candidate, times best-of-k, and
  records the winner;
- results cache in-process and on disk (PADDLE_TPU_AUTOTUNE_CACHE, default
  ~/.cache/paddle_tpu/autotune.json) keyed by backend + signature, so a
  serving/bench process warm-starts instantly;
- the traced attention dispatch (nn/functional/transformer.py) consults
  ``lookup()`` at trace time — shapes are concrete under tracing, timing
  never runs inside a trace;
- everything is budget-capped and falls back to the static heuristic on
  any failure: autotune can only ever improve on the defaults.
"""
import functools
import json
import math
import os
import time

import jax
import jax.numpy as jnp

__all__ = ['autotune_attention', 'lookup', 'attention_signature',
           'make_device_qkv',
           'clear_cache']

_CACHE = {}
_DISK_LOADED = [False]


def _disk_path():
    return os.environ.get(
        'PADDLE_TPU_AUTOTUNE_CACHE',
        os.path.join(os.path.expanduser('~/.cache/paddle_tpu'),
                     'autotune.json'))


def _load_disk():
    if _DISK_LOADED[0]:
        return
    _DISK_LOADED[0] = True
    try:
        with open(_disk_path()) as f:
            for k, v in json.load(f).items():
                _CACHE.setdefault(k, v)
    except Exception:
        pass


def _save_disk():
    try:
        path = _disk_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        merged = {}
        try:   # re-merge: concurrent tuners must not drop each other's work
            with open(path) as f:
                merged.update(json.load(f))
        except Exception:
            pass
        merged.update(_CACHE)
        tmp = path + '.tmp.%d' % os.getpid()
        with open(tmp, 'w') as f:
            json.dump(merged, f, indent=1)
        os.replace(tmp, path)
    except Exception:
        pass


def attention_signature(batch, heads, seq, head_dim, causal, has_kpad,
                        dropout, dtype='bfloat16'):
    return 'attn:%s:%s:b%d_h%d_l%d_d%d_c%d_m%d_p%d' % (
        jax.default_backend(), jnp.dtype(dtype).name, batch, heads, seq,
        head_dim, int(causal), int(has_kpad), int(dropout > 0))


def _valid_decision(d, seq=None):
    if not (isinstance(d, dict) and d.get('mode') in ('flash', 'xla')
            and isinstance(d.get('block_q'), int)
            and isinstance(d.get('block_k'), int)):
        return False
    if d['mode'] == 'flash':
        bq, bk = d['block_q'], d['block_k']
        if bq <= 0 or bk <= 0:
            return False
        if seq is not None and (seq % bq or seq % bk or bq > seq
                                or bk > seq):
            return False
    return True


def lookup(batch, heads, seq, head_dim, causal, has_kpad, dropout,
           dtype='bfloat16'):
    """Cached decision for this signature, or None.

    Returns {'mode': 'flash'|'xla', 'block_q': int, 'block_k': int}.
    Malformed disk entries (hand-edited / format drift) are treated as
    untuned — the dispatch must never crash on cache contents.
    """
    _load_disk()
    d = _CACHE.get(attention_signature(
        batch, heads, seq, head_dim, causal, has_kpad, dropout, dtype))
    return d if _valid_decision(d, seq) else None


def clear_cache():
    _CACHE.clear()
    _DISK_LOADED[0] = False


def _time_step(fn, args, iters=5, warmup=2):
    from ..observability import Stopwatch
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    best = float('inf')
    sw = Stopwatch()
    for _ in range(iters):
        sw.restart()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, sw.elapsed())
    return best


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4, 5))
def _qkv_program(key, batch, heads, seq, head_dim, dtype):
    return tuple(jax.random.normal(kk, (batch, heads, seq, head_dim), dtype)
                 for kk in jax.random.split(key, 3))


def make_device_qkv(batch, heads, seq, head_dim, dtype, seed=0):
    """Three [b,h,s,d] standard-normal tensors generated ON DEVICE as one
    jitted program (compiled once per shape signature per process, zero
    host->device transfer). Benchmark/tuning inputs must never be uploaded
    from host: 50 MB of q/k/v at the b64 h16 s128 d64 bf16 signature
    stalls for hours over the remote tunnel (~3 KB/s effective)."""
    return _qkv_program(jax.random.PRNGKey(seed), batch, heads, seq,
                        head_dim, jnp.dtype(dtype))


def _candidate_blocks(seq, has_kpad):
    """Tile candidates; with a key-padding bias block_k is pinned to the
    full row (the kernel streams the whole bias), so only block_q varies."""
    bs = [b for b in (128, 256, 512, 1024) if seq % b == 0 and b <= seq]
    if has_kpad:
        return [(bq, seq) for bq in bs]
    return [(bq, bk) for bq in bs for bk in bs]


def autotune_attention(batch, heads, seq, head_dim, dtype='bfloat16',
                       causal=False, has_kpad=False, dropout_p=0.0,
                       budget_s=90.0, verbose=False):
    """Time flash block candidates + the XLA path for one shape signature
    (training step: forward + grads wrt q/k/v); record and return the
    winner. No-op (returns the cached decision) when already tuned.
    """
    sig = attention_signature(batch, heads, seq, head_dim, causal,
                              has_kpad, dropout_p, dtype)
    _load_disk()
    if _valid_decision(_CACHE.get(sig), seq):
        return _CACHE[sig]

    from .flash_attention import flash_attention_bhld

    dt = jnp.dtype(dtype)
    q, k, v = make_device_qkv(batch, heads, seq, head_dim, dt)
    kpad = None
    if has_kpad:
        kpad = jnp.zeros((batch, seq), dt)
    seed = jnp.zeros((1, 1), jnp.int32) if dropout_p > 0 else None
    scale = 1.0 / math.sqrt(head_dim)

    def make_flash_step(bq, bk):
        def loss(qq, kk, vv):
            out = flash_attention_bhld(
                qq, kk, vv, causal=causal, scale=scale, kpad_bias=kpad,
                dropout_p=dropout_p, dropout_seed=seed,
                block_q=bq, block_k=bk)
            return jnp.sum(out.astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    def make_xla_step():
        drop_key = jax.random.PRNGKey(0)

        def loss(qq, kk, vv):
            s = jnp.einsum('bhqd,bhkd->bhqk', qq, kk).astype(jnp.float32) \
                * scale
            if causal:
                L = qq.shape[2]
                mask = jnp.tril(jnp.ones((L, L), jnp.bool_))
                s = jnp.where(mask, s, -1e30)
            if kpad is not None:
                s = s + kpad[:, None, None, :].astype(jnp.float32)
            p = jax.nn.softmax(s, axis=-1).astype(qq.dtype)
            if dropout_p > 0:
                # the real XLA fallback applies attention-prob dropout too;
                # the candidates must pay the same costs to compare fairly
                keep = jax.random.bernoulli(drop_key, 1.0 - dropout_p,
                                            p.shape)
                p = jnp.where(keep, p / (1.0 - dropout_p), 0.0)
            out = jnp.einsum('bhqk,bhkd->bhqd', p, vv)
            return jnp.sum(out.astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    deadline = time.monotonic() + budget_s
    results = []   # (seconds, decision-dict)

    def try_candidate(label, decision, builder, force=False):
        if not force and time.monotonic() > deadline and results:
            return
        try:
            t = _time_step(builder(), (q, k, v))
            results.append((t, decision))
            from .. import observability as _obs
            if _obs.enabled():
                # candidate timings belong on the telemetry spine, not
                # only the verbose console (GL014)
                _obs.event('autotune.candidate', sig=sig, label=label,
                           ms=round(t * 1e3, 3))
            if verbose:
                # graftlint: disable=GL014 — opt-in tuning console output;
                # the measurement also lands on the event log above
                print('  autotune %s %s: %.3f ms' % (sig, label, t * 1e3))
        except Exception as e:
            if verbose:
                print('  autotune %s %s: failed (%r)' % (sig, label, e))

    try_candidate('xla', {'mode': 'xla', 'block_q': 0, 'block_k': 0},
                  make_xla_step)
    flash_timed = 0
    if jax.default_backend() == 'tpu':
        cands = _candidate_blocks(seq, has_kpad)
        # the default tiling is always measured even with the budget gone:
        # a decision comparing xla against NO flash candidate could cache a
        # choice worse than the static heuristic
        default = (512, 512) if (512, 512) in cands else \
            (cands[len(cands) // 2] if cands else None)
        for bq, bk in sorted(cands, key=lambda c: c != default):
            before = len(results)
            try_candidate(
                'flash %dx%d' % (bq, bk),
                {'mode': 'flash', 'block_q': bq, 'block_k': bk},
                functools.partial(make_flash_step, bq, bk),
                force=((bq, bk) == default))
            flash_timed += len(results) - before
        if cands and not flash_timed:
            return None   # nothing comparable was measured; don't cache

    if not results:
        return None
    best_t, best = min(results, key=lambda r: r[0])
    best = dict(best, ms=round(best_t * 1e3, 3))
    # record the untuned XLA time too, so benches can report the
    # tuned-vs-untuned delta without re-measuring
    xla_times = [t for t, d in results if d.get('mode') == 'xla']
    if xla_times:
        best['xla_ms'] = round(min(xla_times) * 1e3, 3)
    _CACHE[sig] = best
    _save_disk()
    return best
