"""Flash attention: Pallas TPU kernels, forward AND backward, with dropout.

Replaces the reference's fused attention CUDA path
(paddle/fluid/operators/fused/*attention*). Online-softmax tiling keeps the
(L, L) score matrix out of HBM in both directions: the forward streams K/V
tiles against resident Q tiles and saves only O and the per-row logsumexp;
the backward recomputes probability tiles from (q, k, lse) on the fly inside
two kernels (dQ: grid over Q tiles; dK/dV: grid over K tiles), so no (L, L)
matrix is ever materialized.

Features:
- causal and non-causal attention;
- additive key-padding bias of shape (B, Lk) — the form BERT's (B, 1, 1, L)
  padding mask reduces to;
- attention-probability dropout INSIDE the kernel: the keep-mask for tile
  (bh, q_block, k_block) is regenerated from the TPU hardware PRNG
  (pltpu.prng_seed keyed on the tile coordinates) identically in the forward
  and both backward kernels, so no (L, L) mask is stored.

The non-dropout kernels accept interpret=True so their numerics are testable
on the CPU backend (tests/test_flash_attention.py); the interpret emulation of
prng_random_bits is a zero-stub, so the dropout path is validated on real TPU
hardware (tests marked tpu-only + finite-difference check in
tests/test_flash_attention.py::test_flash_dropout_*).

On non-TPU backends the public entry point falls back to plain-XLA attention
with identical semantics (dropout there uses jax.random — same distribution,
different stream).
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

NEG_INF = -1e30
LSE_EMPTY = 1e30  # lse sentinel for fully-masked rows: exp(s - BIG) == 0


def _attn_reference(q, k, v, causal, scale, kpad_bias=None, dropout_p=0.0,
                    dropout_key=None):
    """Plain XLA attention on (B, H, L, D) — fallback + ground truth.

    kpad_bias: optional (B, Lk) additive bias (0 for keep, large negative for
    masked keys).
    """
    scores = jnp.einsum('bhld,bhmd->bhlm', q, k) * scale
    if kpad_bias is not None:
        scores = scores + kpad_bias[:, None, None, :].astype(scores.dtype)
    if causal:
        L, M = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((L, M), dtype=bool))
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout_p > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          jnp.zeros_like(probs))
    return jnp.einsum('bhlm,bhmd->bhld', probs, v)


def _score_tile(q, k_tile, bias_tile, causal, q_offset, k_offset, scale):
    """(block_q, block_k) scores for one tile pair, masked.

    q/k stay in their native dtype (bf16 on the training path) so the MXU
    runs native-bf16 with fp32 accumulation — upcasting the tiles first would
    force fp32 MXU passes at a fraction of the throughput. The scale is
    applied to the fp32 scores after the matmul.
    """
    s = jnp.dot(q, k_tile.T, preferred_element_type=jnp.float32) * scale
    if bias_tile is not None:
        s = s + bias_tile
    if causal:
        bq, bk = s.shape
        rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        cols = k_offset + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(rows >= cols, s, NEG_INF)
    return s


from ._common import tile_keep_scale as _tile_keep_scale  # noqa: E402


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(*refs, block_k, seq_len, causal, scale, has_bias, dropout_p):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    idx = 3
    bias_ref = seed_ref = None
    if has_bias:
        bias_ref = refs[idx]; idx += 1
    if dropout_p > 0.0:
        seed_ref = refs[idx]; idx += 1
    o_ref, lse_ref = refs[idx:idx + 2]

    q = q_ref[0]                                       # (block_q, d) native
    block_q = q.shape[0]
    q_blk = pl.program_id(1)
    q_offset = q_blk * block_q

    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((block_q, 1), jnp.float32)
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    if causal:
        n_blocks = (q_offset + block_q + block_k - 1) // block_k
        # tiles strictly below the diagonal need no causal mask: the mask's
        # iota/where per tile costs real VPU time, so split the sweep into an
        # unmasked interior phase and a masked diagonal phase. The numerator
        # is clamped non-negative BEFORE the divide: Mosaic lowers // as
        # truncating division, which disagrees with floor on negatives.
        n_full = jnp.maximum(q_offset + 1 - block_k, 0) // block_k
        n_full = jnp.where(q_offset + 1 >= block_k, n_full + 1, 0)
    else:
        n_blocks = seq_len // block_k
        n_full = n_blocks

    def make_body(masked):
        def body(i, carry):
            m_i, l_i, acc_i = carry
            k_tile = k_ref[0, pl.dslice(i * block_k, block_k), :]
            v_tile = v_ref[0, pl.dslice(i * block_k, block_k), :]
            bias_tile = None
            if bias_ref is not None:
                bias_tile = bias_ref[0, :, pl.dslice(i * block_k, block_k)
                                     ].astype(jnp.float32)  # (1, block_k)
            s = _score_tile(q, k_tile, bias_tile, masked, q_offset,
                            i * block_k, scale)
            m_new = jnp.maximum(m_i, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_i - m_new)
            # l accumulates UNdropped p: dropout applies to the normalized
            # probs; the final o = acc / l realizes drop(softmax(s)) @ v.
            l_new = l_i * corr + jnp.sum(p, axis=-1, keepdims=True)
            p_acc = p
            if dropout_p > 0.0:
                nq, nk = seq_len // block_q, seq_len // block_k
                tile_id = (pl.program_id(0) * nq + q_blk) * nk + i
                p_acc = p * _tile_keep_scale(seed_ref, tile_id, p.shape,
                                             dropout_p)
            # p in the value matmul rides the MXU in v's dtype (bf16 on the
            # training path); the accumulator stays fp32
            acc_new = acc_i * corr + jnp.dot(
                p_acc.astype(v_tile.dtype), v_tile,
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new
        return body

    m, l, acc = jax.lax.fori_loop(0, n_full, make_body(False), (m, l, acc))
    if causal:
        m, l, acc = jax.lax.fori_loop(n_full, n_blocks, make_body(True),
                                      (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse = jnp.where(l > 0, m + jnp.log(jnp.maximum(l, 1e-30)), LSE_EMPTY)
    lse_ref[0] = lse.astype(jnp.float32)                # (block_q, 1)


def _flash_forward(q, k, v, kpad_bias, seed, causal, scale, block_q, block_k,
                   dropout_p, interpret):
    b, h, L, d = q.shape
    bq, bk = min(block_q, L), min(block_k, L)
    q3, k3, v3 = (t.reshape(b * h, L, d) for t in (q, k, v))
    has_bias = kpad_bias is not None
    kernel = functools.partial(_fwd_kernel, block_k=bk, seq_len=L,
                               causal=causal, scale=scale, has_bias=has_bias,
                               dropout_p=dropout_p)
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
        pl.BlockSpec((1, L, d), lambda bh, i: (bh, 0, 0)),
        pl.BlockSpec((1, L, d), lambda bh, i: (bh, 0, 0)),
    ]
    args = [q3, k3, v3]
    if has_bias:
        # (B, 1, L) so the block shape (1, 1, L) satisfies TPU tiling rules
        in_specs.append(
            pl.BlockSpec((1, 1, L), lambda bh, i, h=h: (bh // h, 0, 0)))
        args.append(kpad_bias.astype(jnp.float32)[:, None, :])
    if dropout_p > 0.0:
        in_specs.append(pl.BlockSpec((1, 1), lambda bh, i: (0, 0)))
        args.append(seed)
    o, lse = pl.pallas_call(
        kernel,
        grid=(b * h, L // bq),
        in_specs=in_specs,
        out_specs=(pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
                   pl.BlockSpec((1, bq, 1), lambda bh, i: (bh, i, 0))),
        out_shape=(jax.ShapeDtypeStruct((b * h, L, d), q.dtype),
                   jax.ShapeDtypeStruct((b * h, L, 1), jnp.float32)),
        interpret=interpret,
    )(*args)
    return o.reshape(b, h, L, d), lse.reshape(b, h, L)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _dq_kernel(*refs, block_k, seq_len, causal, scale, has_bias, dropout_p):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    idx = 6
    bias_ref = seed_ref = None
    if has_bias:
        bias_ref = refs[idx]; idx += 1
    if dropout_p > 0.0:
        seed_ref = refs[idx]; idx += 1
    dq_ref = refs[idx]

    q = q_ref[0]                                        # (block_q, d) native
    do = do_ref[0]                                      # (block_q, d) native
    lse = lse_ref[0].astype(jnp.float32)                # (block_q, 1)
    delta = delta_ref[0].astype(jnp.float32)            # (block_q, 1)
    block_q = q.shape[0]
    q_blk = pl.program_id(1)
    q_offset = q_blk * block_q

    if causal:
        n_blocks = (q_offset + block_q + block_k - 1) // block_k
        # clamp-then-divide: Mosaic // truncates, floor needed on negatives
        n_full = jnp.maximum(q_offset + 1 - block_k, 0) // block_k
        n_full = jnp.where(q_offset + 1 >= block_k, n_full + 1, 0)
    else:
        n_blocks = seq_len // block_k
        n_full = n_blocks

    def make_body(masked):
        def body(i, dq_acc):
            k_tile = k_ref[0, pl.dslice(i * block_k, block_k), :]
            v_tile = v_ref[0, pl.dslice(i * block_k, block_k), :]
            bias_tile = None
            if bias_ref is not None:
                bias_tile = bias_ref[0, :, pl.dslice(i * block_k, block_k)
                                     ].astype(jnp.float32)  # (1, block_k)
            s = _score_tile(q, k_tile, bias_tile, masked, q_offset,
                            i * block_k, scale)
            p = jnp.exp(s - lse)                        # (block_q, block_k)
            dp = jnp.dot(do, v_tile.T, preferred_element_type=jnp.float32)
            if dropout_p > 0.0:
                nq, nk = seq_len // block_q, seq_len // block_k
                tile_id = (pl.program_id(0) * nq + q_blk) * nk + i
                dp = dp * _tile_keep_scale(seed_ref, tile_id, dp.shape,
                                           dropout_p)
            ds = p * (dp - delta)
            return dq_acc + jnp.dot(ds.astype(k_tile.dtype), k_tile,
                                    preferred_element_type=jnp.float32)
        return body

    zero_dq = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    dq = jax.lax.fori_loop(0, n_full, make_body(False), zero_dq)
    if causal:
        dq = jax.lax.fori_loop(n_full, n_blocks, make_body(True), dq)
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(*refs, block_q, seq_len, causal, scale, has_bias, dropout_p):
    refs = list(refs)
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref = refs[:6]
    idx = 6
    bias_ref = seed_ref = None
    if has_bias:
        bias_ref = refs[idx]; idx += 1
    if dropout_p > 0.0:
        seed_ref = refs[idx]; idx += 1
    dk_ref, dv_ref = refs[idx:idx + 2]

    k = k_ref[0]                                        # (block_k, d) native
    v = v_ref[0]
    block_k = k.shape[0]
    k_blk = pl.program_id(1)
    k_offset = k_blk * block_k
    bias_tile = None
    if bias_ref is not None:
        bias_tile = bias_ref[0].astype(jnp.float32)     # (1, block_k)

    n_q_blocks = seq_len // block_q
    if causal:
        start = k_offset // block_q
        # q tiles whose every row >= every col of this k tile are unmasked:
        # i*block_q >= k_offset + block_k - 1
        start_full = (k_offset + block_k - 1 + block_q - 1) // block_q
    else:
        start = 0
        start_full = 0

    def make_body(masked):
        def body(i, carry):
            dk_acc, dv_acc = carry
            q_tile = q_ref[0, pl.dslice(i * block_q, block_q), :]
            do_tile = do_ref[0, pl.dslice(i * block_q, block_q), :]
            lse = lse_ref[0, pl.dslice(i * block_q, block_q), :
                          ].astype(jnp.float32)         # (block_q, 1)
            delta = delta_ref[0, pl.dslice(i * block_q, block_q), :
                              ].astype(jnp.float32)     # (block_q, 1)
            s = _score_tile(q_tile, k, bias_tile, masked, i * block_q,
                            k_offset, scale)
            p = jnp.exp(s - lse)                        # (block_q, block_k)
            p_drop = p
            dp = jnp.dot(do_tile, v.T, preferred_element_type=jnp.float32)
            if dropout_p > 0.0:
                nq, nk = seq_len // block_q, seq_len // block_k
                tile_id = (pl.program_id(0) * nq + i) * nk + k_blk
                keep_scale = _tile_keep_scale(seed_ref, tile_id, p.shape,
                                              dropout_p)
                p_drop = p * keep_scale
                dp = dp * keep_scale
            dv_acc = dv_acc + jnp.dot(p_drop.T.astype(do_tile.dtype), do_tile,
                                      preferred_element_type=jnp.float32)
            ds = p * (dp - delta)
            dk_acc = dk_acc + jnp.dot(ds.T.astype(q_tile.dtype), q_tile,
                                      preferred_element_type=jnp.float32)
            return dk_acc, dv_acc
        return body

    zero = jnp.zeros((block_k, k.shape[1]), jnp.float32)
    if causal:
        bound = jnp.minimum(jnp.maximum(start_full, start), n_q_blocks)
        dk, dv = jax.lax.fori_loop(start, bound, make_body(True),
                                   (zero, zero))
        dk, dv = jax.lax.fori_loop(bound, n_q_blocks, make_body(False),
                                   (dk, dv))
    else:
        dk, dv = jax.lax.fori_loop(start, n_q_blocks, make_body(False),
                                   (zero, zero))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, kpad_bias, seed, g, causal, scale,
                    block_q, block_k, dropout_p, interpret):
    b, h, L, d = q.shape
    bq, bk = min(block_q, L), min(block_k, L)
    q3, k3, v3, o3, g3 = (t.reshape(b * h, L, d) for t in (q, k, v, o, g))
    lse3 = lse.reshape(b * h, L, 1)
    delta = jnp.sum(g3.astype(jnp.float32) * o3.astype(jnp.float32),
                    axis=-1, keepdims=True)             # (BH, L, 1)
    has_bias = kpad_bias is not None
    extra_args = []
    if has_bias:
        extra_args.append(kpad_bias.astype(jnp.float32)[:, None, :])  # (B,1,L)
    if dropout_p > 0.0:
        extra_args.append(seed)

    tile_qd = pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0))
    tile_q1 = pl.BlockSpec((1, bq, 1), lambda bh, i: (bh, i, 0))
    full_ld = pl.BlockSpec((1, L, d), lambda bh, i: (bh, 0, 0))
    full_l1 = pl.BlockSpec((1, L, 1), lambda bh, i: (bh, 0, 0))
    bias_full = pl.BlockSpec((1, 1, L), lambda bh, i, h=h: (bh // h, 0, 0))
    seed_spec = pl.BlockSpec((1, 1), lambda bh, i: (0, 0))

    dq_in = [tile_qd, full_ld, full_ld, tile_qd, tile_q1, tile_q1]
    if has_bias:
        dq_in.append(bias_full)
    if dropout_p > 0.0:
        dq_in.append(seed_spec)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=bk, seq_len=L, causal=causal,
                          scale=scale, has_bias=has_bias, dropout_p=dropout_p),
        grid=(b * h, L // bq),
        in_specs=dq_in,
        out_specs=tile_qd,
        out_shape=jax.ShapeDtypeStruct((b * h, L, d), q.dtype),
        interpret=interpret,
    )(q3, k3, v3, g3, lse3, delta, *extra_args)

    tile_kd = pl.BlockSpec((1, bk, d), lambda bh, j: (bh, j, 0))
    bias_tile = pl.BlockSpec((1, 1, bk), lambda bh, j, h=h: (bh // h, 0, j))
    dkv_in = [full_ld, tile_kd, tile_kd, full_ld, full_l1, full_l1]
    if has_bias:
        dkv_in.append(bias_tile)
    if dropout_p > 0.0:
        dkv_in.append(seed_spec)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=bq, seq_len=L, causal=causal,
                          scale=scale, has_bias=has_bias, dropout_p=dropout_p),
        grid=(b * h, L // bk),
        in_specs=dkv_in,
        out_specs=(tile_kd, tile_kd),
        out_shape=(jax.ShapeDtypeStruct((b * h, L, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, L, d), v.dtype)),
        interpret=interpret,
    )(q3, k3, v3, g3, lse3, delta, *extra_args)

    return (dq.reshape(b, h, L, d), dk.reshape(b, h, L, d),
            dv.reshape(b, h, L, d))


# ---------------------------------------------------------------------------
# custom-vjp wrapper + public entry
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, kpad_bias, seed, causal, scale, block_q, block_k,
           dropout_p, interpret):
    o, _ = _flash_forward(q, k, v, kpad_bias, seed, causal, scale, block_q,
                          block_k, dropout_p, interpret)
    return o


def _flash_fwd_rule(q, k, v, kpad_bias, seed, causal, scale, block_q, block_k,
                    dropout_p, interpret):
    o, lse = _flash_forward(q, k, v, kpad_bias, seed, causal, scale, block_q,
                            block_k, dropout_p, interpret)
    return o, (q, k, v, o, lse, kpad_bias, seed)


def _flash_bwd_rule(causal, scale, block_q, block_k, dropout_p, interpret,
                    res, g):
    q, k, v, o, lse, kpad_bias, seed = res
    dq, dk, dv = _flash_backward(q, k, v, o, lse, kpad_bias, seed, g, causal,
                                 scale, block_q, block_k, dropout_p, interpret)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_bhld(q, k, v, causal=False, scale=None, kpad_bias=None,
                         dropout_p=0.0, dropout_seed=None,
                         block_q=512, block_k=512, interpret=False):
    """Flash attention on (B, H, L, D) tensors.

    kpad_bias: optional (B, Lk) additive key-padding bias (0 = keep, -1e4/-inf
    style = masked). dropout_p: attention-probability dropout rate; when > 0,
    dropout_seed must be an int32 array of shape (1, 1) (the keep-mask is a
    deterministic function of it). Falls back to plain-XLA attention when
    Pallas is unavailable (non-TPU backend and interpret=False) or L doesn't
    tile.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    L = q.shape[2]
    dropout_p = float(dropout_p)
    if kpad_bias is not None:
        # the fwd/dq kernels stream bias columns with an in-kernel dynamic
        # slice of the minor dim, which Mosaic cannot lower for block_k < L;
        # key-padding attention is non-causal and reads every K anyway, so
        # stream the full row
        block_k = L
    usable = (_HAS_PLTPU and (interpret is not False
                              or jax.default_backend() == 'tpu')
              and k.shape[2] == L
              and L % min(block_q, L) == 0 and L % min(block_k, L) == 0)
    if dropout_p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    if not usable:
        key = None
        if dropout_p > 0.0:
            key = jax.random.PRNGKey(0)
            key = jax.random.fold_in(key, dropout_seed.reshape(())
                                     .astype(jnp.uint32))
        return _attn_reference(q, k, v, causal, scale, kpad_bias,
                               dropout_p, key)
    seed = (dropout_seed if dropout_seed is not None
            else jnp.zeros((1, 1), jnp.int32))
    return _flash(q, k, v, kpad_bias, seed, causal, scale, block_q, block_k,
                  dropout_p, interpret)
