"""Flash attention: Pallas TPU kernel (forward) + recompute backward.

Replaces the reference's fused attention CUDA path
(paddle/fluid/operators/fused/*attention*). Online-softmax tiling keeps the
(L, L) score matrix out of HBM: Q tiles stay resident in VMEM while K/V tiles
stream through, which is the whole trick on a bandwidth-bound chip.

Backward uses rematerialized plain-XLA attention (flash backward kernel is a
planned optimization) via jax.custom_vjp.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

NEG_INF = -1e30


def _attn_reference(q, k, v, causal, scale):
    """Plain XLA attention on (B, H, L, D) — used for backward + fallback."""
    scores = jnp.einsum('bhld,bhmd->bhlm', q, k) * scale
    if causal:
        L, M = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((L, M), dtype=bool))
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum('bhlm,bhmd->bhld', probs, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, seq_len, causal, scale):
    """Grid: (batch*heads, q_blocks). One Q tile vs streamed K/V tiles."""
    q = q_ref[0].astype(jnp.float32) * scale          # (block_q, d)
    block_q = q.shape[0]
    q_idx = pl.program_id(1)
    q_offset = q_idx * block_q

    m = jnp.full((block_q, 1), NEG_INF, jnp.float32)   # running max
    l = jnp.zeros((block_q, 1), jnp.float32)           # running denom
    acc = jnp.zeros((block_q, q.shape[1]), jnp.float32)

    num_k_blocks = seq_len // block_k
    if causal:
        # only iterate K blocks that intersect the causal triangle
        num_k_blocks_needed = (q_offset + block_q + block_k - 1) // block_k
    else:
        num_k_blocks_needed = num_k_blocks

    def body(i, carry):
        m_i, l_i, acc_i = carry
        k_tile = pl.load(k_ref, (0, pl.dslice(i * block_k, block_k),
                                 pl.dslice(None))).astype(jnp.float32)
        v_tile = pl.load(v_ref, (0, pl.dslice(i * block_k, block_k),
                                 pl.dslice(None))).astype(jnp.float32)
        s = jnp.dot(q, k_tile.T, preferred_element_type=jnp.float32)
        if causal:
            rows = q_offset + jax.lax.broadcasted_iota(jnp.int32,
                                                       (block_q, block_k), 0)
            cols = i * block_k + jax.lax.broadcasted_iota(jnp.int32,
                                                          (block_q, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m_i, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_i - m_new)
        l_new = l_i * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc_i * corr + jnp.dot(p, v_tile,
                                         preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_k_blocks_needed, body, (m, l, acc))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_forward(q, k, v, causal, scale, block_q, block_k):
    b, h, L, d = q.shape
    bq = min(block_q, L)
    bk = min(block_k, L)
    if L % bq or L % bk:
        return _attn_reference(q, k, v, causal, scale)
    q3 = q.reshape(b * h, L, d)
    k3 = k.reshape(b * h, L, d)
    v3 = v.reshape(b * h, L, d)
    kernel = functools.partial(_flash_kernel, block_k=bk, seq_len=L,
                               causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(b * h, L // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, L, d), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, L, d), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, i: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, L, d), q.dtype),
    )(q3, k3, v3)
    return out.reshape(b, h, L, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, scale, block_q, block_k):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    return _flash_forward(q, k, v, causal, scale, block_q, block_k), (q, k, v)


def _flash_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _attn_reference(a, b, c, causal, scale),
                     q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention_bhld(q, k, v, causal=False, scale=None,
                         block_q=512, block_k=512):
    """q/k/v: (B, H, L, D). Returns (B, H, L, D)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if jax.default_backend() != 'tpu' or not _HAS_PLTPU:
        return _attn_reference(q, k, v, causal, scale)
    try:
        return _flash(q, k, v, causal, scale, block_q, block_k)
    except Exception:
        return _attn_reference(q, k, v, causal, scale)
