"""Fused dropout + residual-add + LayerNorm: Pallas TPU kernel.

Replaces the reference's fused_dropout_add / layer_norm CUDA stack
(paddle/fluid/operators/fused/fused_dropout_helper.h,
layer_norm_op.cu) with a TPU-native single-pass design. Profiled on v5e
(BERT-large seq512): the unfused path costs three full HBM passes per
sublayer (rng-bits materialization, dropout select, add) before the norm
kernel reads the sum again — ~30 ms/step across 48 sublayer sites. This
kernel reads x and residual once, generates the keep mask from the TPU
hardware PRNG in-register (seeded by tile id, exactly like
flash_attention.py's in-kernel dropout), and writes the normalized output
plus the pre-norm sum in one pass. Measured on v5e BERT-large: +3.8% step
throughput at seq128 and +4.2% at seq512 over the XLA-fused composition
(tools/bench_2x2.py).

Backward: LayerNorm's closed-form gradient runs in plain XLA from the saved
pre-norm sum + row stats (one fused pass); the dropout mask is REGENERATED
from the same (seed, tile) PRNG stream by a small Pallas kernel — the
(N, D) mask is never stored.

Interpret-mode caveat: prng_random_bits is a zero-stub on CPU interpret, so
dropout_p > 0 parity is TPU-only (the p == 0 fused add+norm path is fully
testable on CPU; see tests/test_fused_dropout_norm.py).
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False


from ._common import tile_keep_scale as _keep_scale, row_block as _row_block


def _fwd_kernel(*refs, eps, p, has_w, has_b):
    refs = list(refs)
    x_ref, res_ref = refs[:2]
    idx = 2
    w_ref = b_ref = seed_ref = None
    if has_w:
        w_ref = refs[idx]; idx += 1
    if has_b:
        b_ref = refs[idx]; idx += 1
    if p > 0.0:
        seed_ref = refs[idx]; idx += 1
    y_ref, yin_ref, mean_ref, rstd_ref = refs[idx:idx + 4]

    x = x_ref[...].astype(jnp.float32)                  # (bn, D)
    res = res_ref[...].astype(jnp.float32)
    if p > 0.0:
        x = x * _keep_scale(seed_ref, pl.program_id(0), x.shape, p)
    yin = res + x
    mean = jnp.mean(yin, axis=-1, keepdims=True)
    xc = yin - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd
    if has_w:
        y = y * w_ref[...].astype(jnp.float32)
    if has_b:
        y = y + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    yin_ref[...] = yin.astype(yin_ref.dtype)
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _dmask_kernel(g_ref, seed_ref, out_ref, *, p):
    """dx = d_yin * keep/(1-p) with the regenerated tile mask."""
    g = g_ref[...].astype(jnp.float32)
    out = g * _keep_scale(seed_ref, pl.program_id(0), g.shape, p)
    out_ref[...] = out.astype(out_ref.dtype)




def _fused_fwd(x, res, w, b, seed, eps, p, interpret):
    n, d = x.shape
    bn = _row_block(n)
    has_w, has_b = w is not None, b is not None
    in_specs = [pl.BlockSpec((bn, d), lambda i: (i, 0)),
                pl.BlockSpec((bn, d), lambda i: (i, 0))]
    args = [x, res]
    if has_w:
        in_specs.append(pl.BlockSpec((d,), lambda i: (0,)))
        args.append(w)
    if has_b:
        in_specs.append(pl.BlockSpec((d,), lambda i: (0,)))
        args.append(b)
    if p > 0.0:
        in_specs.append(pl.BlockSpec((1, 1), lambda i: (0, 0)))
        args.append(seed)
    kernel = functools.partial(_fwd_kernel, eps=eps, p=p, has_w=has_w,
                               has_b=has_b)
    y, yin, mean, rstd = pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=in_specs,
        out_specs=(pl.BlockSpec((bn, d), lambda i: (i, 0)),
                   pl.BlockSpec((bn, d), lambda i: (i, 0)),
                   pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                   pl.BlockSpec((bn, 1), lambda i: (i, 0))),
        out_shape=(jax.ShapeDtypeStruct((n, d), x.dtype),
                   jax.ShapeDtypeStruct((n, d), x.dtype),
                   jax.ShapeDtypeStruct((n, 1), jnp.float32),
                   jax.ShapeDtypeStruct((n, 1), jnp.float32)),
        interpret=interpret,
    )(*args)
    return y, yin, mean, rstd


def _apply_dropout_grad(d_yin, seed, p, interpret):
    n, d = d_yin.shape
    bn = _row_block(n)
    return pl.pallas_call(
        functools.partial(_dmask_kernel, p=p),
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, d), lambda i: (i, 0)),
                  pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((bn, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), d_yin.dtype),
        interpret=interpret,
    )(d_yin, seed)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _fdln(x, res, w, b, seed, eps, p, interpret):
    y, _, _, _ = _fused_fwd(x, res, w, b, seed, eps, p, interpret)
    return y


def _fdln_fwd(x, res, w, b, seed, eps, p, interpret):
    y, yin, mean, rstd = _fused_fwd(x, res, w, b, seed, eps, p, interpret)
    return y, (yin, mean, rstd, w, b, seed)


def _fdln_bwd(eps, p, interpret, saved, g):
    yin, mean, rstd, w, b, seed = saved
    d = yin.shape[-1]
    gf = g.astype(jnp.float32)
    yin_f = yin.astype(jnp.float32)
    xhat = (yin_f - mean) * rstd
    dw = jnp.sum(gf * xhat, axis=0).astype(w.dtype) if w is not None else None
    db = jnp.sum(gf, axis=0).astype(b.dtype) if b is not None else None
    gy = gf * w.astype(jnp.float32) if w is not None else gf
    # closed-form LN input gradient
    m1 = jnp.mean(gy, axis=-1, keepdims=True)
    m2 = jnp.mean(gy * xhat, axis=-1, keepdims=True)
    d_yin = (gy - m1 - xhat * m2) * rstd
    d_res = d_yin.astype(yin.dtype)
    if p > 0.0:
        dx = _apply_dropout_grad(d_yin.astype(yin.dtype), seed, p, interpret)
    else:
        dx = d_res
    return dx, d_res, dw, db, None


_fdln.defvjp(_fdln_fwd, _fdln_bwd)


def fused_dropout_add_layer_norm(x, residual, weight=None, bias=None,
                                 dropout_p=0.0, epsilon=1e-5,
                                 dropout_seed=None, interpret=False):
    """y = LayerNorm(residual + dropout(x)) in one TPU pass.

    x/residual: (..., D) — flattened internally to (N, D) row tiles.
    dropout_seed: int32 (1, 1) array, required when dropout_p > 0.
    Falls back to plain XLA composition off-TPU.
    """
    p = float(dropout_p)
    shape = x.shape
    d = shape[-1]
    n = 1
    for s in shape[:-1]:
        n *= s
    usable = (_HAS_PLTPU and _row_block(n) is not None
              and (interpret is not False
                   or jax.default_backend() == 'tpu'))
    if p > 0.0 and dropout_seed is None:
        raise ValueError("dropout_p > 0 requires dropout_seed")
    if not usable:
        xx = x
        if p > 0.0:
            key = jax.random.fold_in(
                jax.random.PRNGKey(0),
                dropout_seed.reshape(()).astype(jnp.uint32))
            keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
            xx = jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))
        yin = residual + xx
        mean = jnp.mean(yin.astype(jnp.float32), axis=-1, keepdims=True)
        xc = yin.astype(jnp.float32) - mean
        var = jnp.mean(xc * xc, axis=-1, keepdims=True)
        y = xc * jax.lax.rsqrt(var + epsilon)
        if weight is not None:
            y = y * weight.astype(jnp.float32)
        if bias is not None:
            y = y + bias.astype(jnp.float32)
        return y.astype(x.dtype)
    seed = (dropout_seed if dropout_seed is not None
            else jnp.zeros((1, 1), jnp.int32))
    y = _fdln(x.reshape(n, d), residual.reshape(n, d), weight, bias, seed,
              float(epsilon), p, interpret)
    return y.reshape(shape)
