"""Fused layer/rms norm: Pallas TPU forward kernels + closed-form backward.

Replaces the reference's fused LayerNorm CUDA kernels
(paddle/fluid/operators/layer_norm_op.cu) with a TPU-native design: one VMEM
pass computes mean/rstd and the normalized output per row tile (no separate
moment kernels), saving only the (N, 1) row statistics for the backward. The
backward is the closed-form layer-norm gradient evaluated in plain XLA from
(x, mean, rstd) — elementwise + row reductions, which XLA fuses into one pass,
so no extra memory traffic is saved by hand-writing it.

Testable on CPU via interpret=True (tests/test_fused_norm.py).
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False


def _ln_fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps,
                   has_w, has_b):
    x = x_ref[...].astype(jnp.float32)                  # (block_n, D)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd
    if has_w:
        y = y * w_ref[...].astype(jnp.float32)
    if has_b:
        y = y + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _rms_fwd_kernel(x_ref, w_ref, y_ref, rstd_ref, *, eps, has_w):
    x = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(ms + eps)
    y = x * rstd
    if has_w:
        y = y * w_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    rstd_ref[...] = rstd


from ._common import row_block as _shared_row_block


def _row_block(n, d):
    # one row tile per grid step; 8-row multiples satisfy TPU sublane tiling
    return _shared_row_block(n)


def _ln_forward(x2, w, b, eps, interpret):
    n, d = x2.shape
    bn = _row_block(n, d)
    has_w, has_b = w is not None, b is not None
    w_arg = w if has_w else jnp.zeros((d,), x2.dtype)
    b_arg = b if has_b else jnp.zeros((d,), x2.dtype)
    kernel = functools.partial(_ln_fwd_kernel, eps=eps, has_w=has_w,
                               has_b=has_b)
    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, d), lambda i: (i, 0)),
                  pl.BlockSpec((d,), lambda i: (0,)),
                  pl.BlockSpec((d,), lambda i: (0,))],
        out_specs=(pl.BlockSpec((bn, d), lambda i: (i, 0)),
                   pl.BlockSpec((bn, 1), lambda i: (i, 0)),
                   pl.BlockSpec((bn, 1), lambda i: (i, 0))),
        out_shape=(jax.ShapeDtypeStruct((n, d), x2.dtype),
                   jax.ShapeDtypeStruct((n, 1), jnp.float32),
                   jax.ShapeDtypeStruct((n, 1), jnp.float32)),
        interpret=interpret,
    )(x2, w_arg, b_arg)
    return y, mean, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_layer_norm2d(x2, w, b, eps, interpret):
    y, _, _ = _ln_forward(x2, w, b, eps, interpret)
    return y


def _ln_fwd_rule(x2, w, b, eps, interpret):
    y, mean, rstd = _ln_forward(x2, w, b, eps, interpret)
    return y, (x2, w, b, mean, rstd)


def _ln_bwd_rule(eps, interpret, res, g):
    x2, w, b, mean, rstd = res
    x = x2.astype(jnp.float32)
    g = g.astype(jnp.float32)
    xhat = (x - mean) * rstd
    gw = g * (w.astype(jnp.float32) if w is not None else 1.0)
    # closed-form LN input grad
    mean_g = jnp.mean(gw, axis=-1, keepdims=True)
    mean_gx = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = (rstd * (gw - mean_g - xhat * mean_gx)).astype(x2.dtype)
    dw = jnp.sum(g * xhat, axis=0).astype(w.dtype) if w is not None else None
    db = jnp.sum(g, axis=0).astype(b.dtype) if b is not None else None
    return dx, dw, db


_fused_layer_norm2d.defvjp(_ln_fwd_rule, _ln_bwd_rule)


def _rms_forward(x2, w, eps, interpret):
    n, d = x2.shape
    bn = _row_block(n, d)
    has_w = w is not None
    w_arg = w if has_w else jnp.zeros((d,), x2.dtype)
    kernel = functools.partial(_rms_fwd_kernel, eps=eps, has_w=has_w)
    y, rstd = pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[pl.BlockSpec((bn, d), lambda i: (i, 0)),
                  pl.BlockSpec((d,), lambda i: (0,))],
        out_specs=(pl.BlockSpec((bn, d), lambda i: (i, 0)),
                   pl.BlockSpec((bn, 1), lambda i: (i, 0))),
        out_shape=(jax.ShapeDtypeStruct((n, d), x2.dtype),
                   jax.ShapeDtypeStruct((n, 1), jnp.float32)),
        interpret=interpret,
    )(x2, w_arg)
    return y, rstd


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _fused_rms_norm2d(x2, w, eps, interpret):
    y, _ = _rms_forward(x2, w, eps, interpret)
    return y


def _rms_fwd_rule(x2, w, eps, interpret):
    y, rstd = _rms_forward(x2, w, eps, interpret)
    return y, (x2, w, rstd)


def _rms_bwd_rule(eps, interpret, res, g):
    x2, w, rstd = res
    x = x2.astype(jnp.float32)
    g = g.astype(jnp.float32)
    xhat = x * rstd
    gw = g * (w.astype(jnp.float32) if w is not None else 1.0)
    mean_gx = jnp.mean(gw * xhat, axis=-1, keepdims=True)
    dx = (rstd * (gw - xhat * mean_gx)).astype(x2.dtype)
    dw = jnp.sum(g * xhat, axis=0).astype(w.dtype) if w is not None else None
    return dx, dw


_fused_rms_norm2d.defvjp(_rms_fwd_rule, _rms_bwd_rule)


def fused_layer_norm(x, weight=None, bias=None, eps=1e-5, interpret=False):
    """Layer norm over the LAST axis of x (any leading shape)."""
    n_rows = 1
    for s in x.shape[:-1]:
        n_rows *= s
    if not (_HAS_PLTPU and _row_block(n_rows, x.shape[-1]) is not None
            and (interpret is not False
                 or jax.default_backend() == 'tpu')):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) * jax.lax.rsqrt(var + eps)
        if weight is not None:
            y = y * weight
        if bias is not None:
            y = y + bias
        return y.astype(x.dtype)
    shape = x.shape
    y = _fused_layer_norm2d(x.reshape(-1, shape[-1]), weight, bias, float(eps),
                            interpret)
    return y.reshape(shape)


def fused_rms_norm(x, weight=None, eps=1e-6, interpret=False):
    """RMS norm over the LAST axis of x (any leading shape)."""
    n_rows = 1
    for s in x.shape[:-1]:
        n_rows *= s
    if not (_HAS_PLTPU and _row_block(n_rows, x.shape[-1]) is not None
            and (interpret is not False
                 or jax.default_backend() == 'tpu')):
        ms = jnp.mean(x * x, axis=-1, keepdims=True)
        y = x * jax.lax.rsqrt(ms + eps)
        if weight is not None:
            y = y * weight
        return y.astype(x.dtype)
    shape = x.shape
    y = _fused_rms_norm2d(x.reshape(-1, shape[-1]), weight, float(eps),
                          interpret)
    return y.reshape(shape)
