"""Metrics. Parity: python/paddle/metric/metrics.py."""
import abc

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ['Metric', 'Accuracy', 'Precision', 'Recall', 'Auc', 'accuracy',
           'EditDistance', 'ChunkEvaluator', 'DetectionMAP',
           'CompositeMetric', 'edit_distance', 'chunk_eval', 'auc',
           'detection_map']


def _np(x):
    return x.numpy() if isinstance(x, Tensor) else np.asarray(x)


class Metric(abc.ABC):
    @abc.abstractmethod
    def reset(self):
        raise NotImplementedError

    @abc.abstractmethod
    def update(self, *args):
        raise NotImplementedError

    @abc.abstractmethod
    def accumulate(self):
        raise NotImplementedError

    @abc.abstractmethod
    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or 'acc'
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., :self.maxk]
        if label_np.ndim == pred_np.ndim:
            if label_np.shape[-1] == pred_np.shape[-1]:
                label_np = np.argmax(label_np, axis=-1)
            else:
                label_np = label_np.squeeze(-1)
        correct = (idx == label_np[..., None]).astype(np.float32)
        return Tensor(jnp.asarray(correct))

    def update(self, correct, *args):
        c = _np(correct)
        num = c.shape[0] if c.ndim else 1
        for i, k in enumerate(self.topk):
            self.total[i] += c[..., :k].sum()
            self.count[i] += num
        return self.total[0] / max(self.count[0], 1)

    def reset(self):
        self.total = [0.] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name='precision', *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds).reshape(-1)
        y = _np(labels).reshape(-1)
        pred_pos = (p > 0.5)
        self.tp += int(np.sum(pred_pos & (y == 1)))
        self.fp += int(np.sum(pred_pos & (y == 0)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name='recall', *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds).reshape(-1)
        y = _np(labels).reshape(-1)
        pred_pos = (p > 0.5)
        self.tp += int(np.sum(pred_pos & (y == 1)))
        self.fn += int(np.sum(~pred_pos & (y == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve='ROC', num_thresholds=4095, name='auc', *args,
                 **kwargs):
        super().__init__()
        self._num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        y = _np(labels).reshape(-1)
        idx = np.clip((p * self._num_thresholds).astype(int), 0,
                      self._num_thresholds)
        np.add.at(self._stat_pos, idx[y == 1], 1)
        np.add.at(self._stat_neg, idx[y != 1], 1)

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1)
        self._stat_neg = np.zeros(self._num_thresholds + 1)

    def accumulate(self):
        tot_pos = np.cumsum(self._stat_pos[::-1])
        tot_neg = np.cumsum(self._stat_neg[::-1])
        auc = np.sum(self._stat_neg[::-1] *
                     (np.concatenate([[0], tot_pos[:-1]]) +
                      self._stat_pos[::-1] / 2.))
        denom = tot_pos[-1] * tot_neg[-1]
        return float(auc / denom) if denom else 0.

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None):
    """Functional metric op. Parity: fluid/layers/metric_op.py:accuracy."""
    from ..core.tensor import apply_op
    from ..tensor._helpers import _t
    input, label = _t(input), _t(label)
    def fn(p, y):
        idx = jnp.argsort(-p, axis=-1)[..., :k]
        yy = y.reshape(-1, 1)
        c = jnp.any(idx == yy, axis=-1)
        return jnp.mean(c.astype(jnp.float32))
    return apply_op(fn, (input, label), differentiable=False)


# fluid.metrics extras (EditDistance, ChunkEvaluator, DetectionMAP,
# CompositeMetric) + their host-side ops
from .extras import (EditDistance, ChunkEvaluator, DetectionMAP,  # noqa: E402
                     CompositeMetric, edit_distance, chunk_eval, auc,
                     detection_map)


from . import metrics  # noqa: E402,F401  (paddle.metric.metrics module path)


def __getattr__(name):
    # cos_sim / mean_iou: the reference re-exports these fluid.layers ops
    # into paddle.metric (python/paddle/metric/__init__.py); lazy to avoid
    # an import cycle with the fluid package
    if name in ('cos_sim', 'mean_iou'):
        from ..fluid import layers as _L
        return getattr(_L, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
