"""fluid.metrics extras + their underlying ops.

Parity: the reference's python/paddle/fluid/metrics.py (EditDistance,
DetectionMAP, ChunkEvaluator, CompositeMetric) and the ops feeding them
(edit_distance_op.cc, chunk_eval_op.cc, detection_map_op.cc,
fluid/layers/metric_op.py auc). The reference computes all of these on
CPU inside the executor; here they are host-side numpy/python on padded
arrays — metrics are eval-loop bookkeeping, not MXU work — and none of
them may be called under jit tracing.
"""
import numpy as np

from . import Metric, _np
from ..core.tensor import Tensor

__all__ = ['EditDistance', 'DetectionMAP', 'ChunkEvaluator',
           'CompositeMetric', 'edit_distance', 'chunk_eval', 'auc',
           'detection_map']


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------

def _levenshtein(a, b):
    """Classic O(len(a)*len(b)) DP (plain lists — numpy scalar boxing makes
    the per-cell loop several times slower)."""
    la, lb = len(a), len(b)
    if la == 0:
        return lb
    if lb == 0:
        return la
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        ai = a[i - 1]
        cur = [i] + [0] * lb
        for j in range(1, lb + 1):
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1,
                         prev[j - 1] + (ai != b[j - 1]))
        prev = cur
    return prev[lb]


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None, name=None):
    """Levenshtein distance between each hyp/ref id sequence pair.

    input/label: [B, T] padded int ids; *_length: [B] valid lengths
    (default: full width). ``normalized`` divides by the reference length.
    Returns ([B, 1] float32 distances, [1] sequence count), the reference
    op's two outputs.
    """
    inp, lab = _np(input), _np(label)
    B = inp.shape[0]
    in_len = _np(input_length).astype(int) if input_length is not None \
        else np.full(B, inp.shape[1], int)
    lb_len = _np(label_length).astype(int) if label_length is not None \
        else np.full(B, lab.shape[1], int)
    ignored = set(ignored_tokens or ())
    out = np.empty((B, 1), np.float32)
    for i in range(B):
        a = [t for t in inp[i, :in_len[i]].tolist() if t not in ignored]
        b = [t for t in lab[i, :lb_len[i]].tolist() if t not in ignored]
        d = _levenshtein(a, b)
        if normalized:
            d = d / max(len(b), 1)
        out[i, 0] = d
    return Tensor(out), Tensor(np.array([B], np.int64))


def _extract_chunks(tags, scheme, num_chunk_types, excluded=()):
    """(begin, end, type) chunks from a tag sequence.

    Tag encoding follows the reference chunk_eval op: for IOB each chunk
    type t owns tags (2t: B-t, 2t+1: I-t); IOE uses (I-t, E-t); IOBES uses
    4 tags per type (B, I, E, S); 'plain' gives each type a single tag.
    """
    chunks = []
    start, ctype = None, None

    def close(end):
        nonlocal start, ctype
        if start is not None and ctype not in excluded:
            chunks.append((start, end, ctype))
        start, ctype = None, None

    for pos, tag in enumerate(tags):
        tag = int(tag)
        if scheme == 'plain':
            t, kind = tag, 'S'
        elif scheme == 'IOB':
            t, kind = divmod(tag, 2)
            kind = 'B' if kind == 0 else 'I'
        elif scheme == 'IOE':
            t, kind = divmod(tag, 2)
            kind = 'I' if kind == 0 else 'E'
        elif scheme == 'IOBES':
            t, kind = divmod(tag, 4)
            kind = 'BIES'[kind]
        else:
            raise ValueError("unknown chunk scheme %r" % scheme)
        if t >= num_chunk_types:         # outside tag
            close(pos)
            continue
        if scheme == 'plain':
            if ctype != t:
                close(pos)
                start, ctype = pos, t
            continue
        if kind in ('B', 'S') or ctype != t:
            close(pos)
            start, ctype = pos, t
        if kind in ('E', 'S'):
            close(pos + 1)
    close(len(tags))
    return set(chunks)


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """Chunk-level precision/recall/F1 between inferred and label tags.

    input/label: [B, T] padded tag ids; seq_length: [B]. Returns the
    reference op's six outputs: (precision, recall, f1, num_infer_chunks,
    num_label_chunks, num_correct_chunks).
    """
    inf, lab = _np(input), _np(label)
    B = inf.shape[0]
    lens = _np(seq_length).astype(int) if seq_length is not None \
        else np.full(B, inf.shape[1], int)
    excluded = tuple(excluded_chunk_types or ())
    n_inf = n_lab = n_cor = 0
    for i in range(B):
        ci = _extract_chunks(inf[i, :lens[i]], chunk_scheme,
                             num_chunk_types, excluded)
        cl = _extract_chunks(lab[i, :lens[i]], chunk_scheme,
                             num_chunk_types, excluded)
        n_inf += len(ci)
        n_lab += len(cl)
        n_cor += len(ci & cl)
    p = n_cor / n_inf if n_inf else 0.0
    r = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * p * r / (p + r) if p + r else 0.0
    mk = lambda v, dt: Tensor(np.array([v], dt))
    return (mk(p, np.float32), mk(r, np.float32), mk(f1, np.float32),
            mk(n_inf, np.int64), mk(n_lab, np.int64), mk(n_cor, np.int64))


def auc(input, label, curve='ROC', num_thresholds=4095, topk=1,
        slide_steps=1):
    """ROC-AUC of positive-class scores via threshold buckets (the
    reference metric_op.py auc accumulates the same histogram state).

    input: [B, 2] class probabilities (positive = column 1) or [B] scores;
    label: [B] / [B, 1] binary. Returns a scalar float32 Tensor.
    Only curve='ROC' is implemented; topk/slide_steps are accepted for
    signature parity but this computes one-shot (non-windowed) AUC.
    """
    if curve != 'ROC':
        raise NotImplementedError(
            "auc: only curve='ROC' is implemented (got %r)" % curve)
    x, y = _np(input), _np(label).reshape(-1)
    scores = x[:, 1] if x.ndim == 2 else x
    idx = np.clip((scores * num_thresholds).astype(int), 0, num_thresholds)
    pos = y.astype(bool)
    stat_pos = np.bincount(idx[pos], minlength=num_thresholds + 1) \
        .astype(np.float64)
    stat_neg = np.bincount(idx[~pos], minlength=num_thresholds + 1) \
        .astype(np.float64)
    # integrate TPR/FPR from the highest threshold down (trapezoid rule)
    tot_pos = stat_pos.sum()
    tot_neg = stat_neg.sum()
    if tot_pos == 0 or tot_neg == 0:
        return Tensor(np.array(0.0, np.float32))
    area = 0.0
    tp = fp = 0.0
    for i in range(num_thresholds, -1, -1):
        new_tp = tp + stat_pos[i]
        new_fp = fp + stat_neg[i]
        area += (new_fp - fp) * (tp + new_tp) / 2.0
        tp, fp = new_tp, new_fp
    return Tensor(np.array(area / (tot_pos * tot_neg), np.float32))


def detection_map(detect_res, gt_label, gt_box, class_num,
                  overlap_threshold=0.5, ap_version='integral',
                  evaluate_difficult=True):
    """mAP over one batch of detections (reference detection_map_op.cc).

    detect_res: list (per image) of [k, 6] arrays (label, score, x1, y1,
    x2, y2); gt_label/gt_box: lists of [m] labels and [m, 4] boxes.
    Returns the scalar mAP. There is no difficult-flag input here, so only
    evaluate_difficult=True (count every GT) is supported.
    """
    if not evaluate_difficult:
        raise NotImplementedError(
            "detection_map: no difficult-flag input exists in this API; "
            "only evaluate_difficult=True is supported")
    # gather per-class scored matches
    tps = {c: [] for c in range(class_num)}
    n_gt = {c: 0 for c in range(class_num)}
    for det, labs, boxes in zip(detect_res, gt_label, gt_box):
        det = _np(det).reshape(-1, 6)
        labs = _np(labs).reshape(-1).astype(int)
        boxes = _np(boxes).reshape(-1, 4)
        for c in labs:
            if 0 <= int(c) < class_num:   # e.g. background ids are skipped
                n_gt[int(c)] += 1
        matched = set()
        order = np.argsort(-det[:, 1])
        for j in order:
            c, score = int(det[j, 0]), det[j, 1]
            if not 0 <= c < class_num:   # incl. the -1 padding rows that
                continue                 # multiclass_nms emits
            best_iou, best_g = 0.0, -1
            for g in range(len(labs)):
                if labs[g] != c or g in matched:
                    continue
                iou = _iou(det[j, 2:6], boxes[g])
                if iou > best_iou:
                    best_iou, best_g = iou, g
            if best_iou >= overlap_threshold and best_g >= 0:
                matched.add(best_g)
                tps[c].append((score, 1))
            else:
                tps[c].append((score, 0))
    aps = []
    for c in range(class_num):
        if n_gt[c] == 0:
            continue
        pairs = sorted(tps[c], key=lambda p: -p[0])
        tp_cum = np.cumsum([p[1] for p in pairs]) if pairs else np.array([])
        if len(tp_cum) == 0:
            aps.append(0.0)
            continue
        fp_cum = np.arange(1, len(pairs) + 1) - tp_cum
        recall = tp_cum / n_gt[c]
        precision = tp_cum / (tp_cum + fp_cum)
        if ap_version == '11point':
            ap = np.mean([precision[recall >= r].max(initial=0.0)
                          for r in np.linspace(0, 1, 11)])
        else:   # integral
            ap = 0.0
            prev_r = 0.0
            for p, r in zip(precision, recall):
                ap += p * (r - prev_r)
                prev_r = r
        aps.append(float(ap))
    return Tensor(np.array(np.mean(aps) if aps else 0.0, np.float32))


def _iou(a, b):
    ix = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    iy = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    inter = ix * iy
    ua = ((a[2] - a[0]) * (a[3] - a[1]) +
          (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return inter / ua if ua > 0 else 0.0


# ---------------------------------------------------------------------------
# metric accumulators
# ---------------------------------------------------------------------------

class EditDistance(Metric):
    """Accumulates average edit distance + instance error rate
    (reference fluid/metrics.py EditDistance)."""

    def __init__(self, name='edit_distance'):
        self._name = name
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num=None):
        d = _np(distances).reshape(-1)
        self.total_distance += float(d.sum())
        self.seq_num += int(seq_num if seq_num is not None else len(d))
        self.instance_error += int((d > 0).sum())

    def accumulate(self):
        """Returns (avg_distance, instance_error_rate)."""
        if self.seq_num == 0:
            return 0.0, 0.0
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)

    def name(self):
        return self._name


class ChunkEvaluator(Metric):
    """Accumulates chunk counts -> corpus precision/recall/F1
    (reference fluid/metrics.py ChunkEvaluator)."""

    def __init__(self, name='chunk'):
        self._name = name
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(_np(num_infer_chunks).sum())
        self.num_label_chunks += int(_np(num_label_chunks).sum())
        self.num_correct_chunks += int(_np(num_correct_chunks).sum())

    def accumulate(self):
        """Returns (precision, recall, f1)."""
        p = self.num_correct_chunks / self.num_infer_chunks \
            if self.num_infer_chunks else 0.0
        r = self.num_correct_chunks / self.num_label_chunks \
            if self.num_label_chunks else 0.0
        f1 = 2 * p * r / (p + r) if p + r else 0.0
        return p, r, f1

    def name(self):
        return self._name


class DetectionMAP(Metric):
    """Accumulates detection batches -> mAP (reference DetectionMAP wraps
    the detection_map op per batch; here batches are appended and the map
    recomputed over everything seen)."""

    def __init__(self, class_num, overlap_threshold=0.5,
                 ap_version='integral', name='mAP'):
        self.class_num = class_num
        self.overlap_threshold = overlap_threshold
        self.ap_version = ap_version
        self._name = name
        self.reset()

    def reset(self):
        self._det, self._lab, self._box = [], [], []

    def update(self, detect_res, gt_label, gt_box):
        self._det.extend(detect_res)
        self._lab.extend(gt_label)
        self._box.extend(gt_box)

    def accumulate(self):
        return float(detection_map(
            self._det, self._lab, self._box, self.class_num,
            self.overlap_threshold, self.ap_version).numpy())

    def name(self):
        return self._name


class CompositeMetric(Metric):
    """Bundle of metrics updated together (reference CompositeMetric)."""

    def __init__(self, name='composite'):
        self._name = name
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, *args, **kwargs):
        for m in self._metrics:
            m.update(*args, **kwargs)

    def accumulate(self):
        return [m.accumulate() for m in self._metrics]

    def name(self):
        return self._name
