"""``paddle.metric.metrics`` module path (the reference's implementation
module, re-exported: python/paddle/metric/metrics.py). One implementation
in :mod:`paddle_tpu.metric`, two import paths."""
from . import Metric, Accuracy, Precision, Recall, Auc  # noqa: F401

__all__ = ['Metric', 'Accuracy', 'Precision', 'Recall', 'Auc']
