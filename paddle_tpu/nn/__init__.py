"""paddle_tpu.nn. Parity: python/paddle/nn/__init__.py."""
from .layer_base import Layer, functional_call, state_values, param_values, \
    buffer_values, load_state_values
from . import functional
from . import initializer
from .initializer import ParamAttr
from .clip import (ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
                   GradientClipByValue, GradientClipByNorm,
                   GradientClipByGlobalNorm, clip_grad_norm_)
from .regularizer import L1Decay, L2Decay

from .layer.container import Sequential, LayerList, ParameterList, LayerDict
from .layer.common import (Identity, Linear, Embedding, Flatten, Dropout,
                           Dropout2D, Dropout3D, AlphaDropout, Upsample,
                           UpsamplingNearest2D, UpsamplingBilinear2D, Pad1D,
                           Pad2D, Pad3D, ZeroPad2D, CosineSimilarity,
                           PixelShuffle, PixelUnshuffle, Bilinear, Unfold, Fold)
from .layer.conv import (Conv1D, Conv2D, Conv3D, Conv1DTranspose,
                         Conv2DTranspose, Conv3DTranspose)
from .layer.pooling import (MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D,
                            AvgPool2D, AvgPool3D, AdaptiveAvgPool1D,
                            AdaptiveAvgPool2D, AdaptiveAvgPool3D,
                            AdaptiveMaxPool1D, AdaptiveMaxPool2D,
                            AdaptiveMaxPool3D)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                         SyncBatchNorm, LayerNorm, RMSNorm, GroupNorm,
                         InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
                         LocalResponseNorm, SpectralNorm)
from .layer.activation import (ReLU, ReLU6, LeakyReLU, PReLU, RReLU, ELU, CELU,
                               GELU, Sigmoid, Hardsigmoid, Hardswish,
                               Hardshrink, Hardtanh, Softplus, Softshrink,
                               Softsign, Swish, Silu, Mish, Tanh, Tanhshrink,
                               ThresholdedReLU, LogSigmoid, LogSoftmax, Softmax,
                               Maxout, GLU, SELU)
from .layer.loss import (CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss,
                         BCEWithLogitsLoss, KLDivLoss, SmoothL1Loss,
                         MarginRankingLoss, CTCLoss, HingeEmbeddingLoss,
                         CosineEmbeddingLoss, TripletMarginLoss)
from .layer.rnn import (RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN,
                        BiRNN, SimpleRNN, LSTM, GRU)
from .decode import (Decoder, BeamSearchDecoder, dynamic_decode, DecodeHelper,
                     TrainingHelper, GreedyEmbeddingHelper,
                     SampleEmbeddingHelper, BasicDecoder)
from .layer.transformer import (MultiHeadAttention, TransformerEncoderLayer,
                                TransformerEncoder, TransformerDecoderLayer,
                                TransformerDecoder, Transformer)
from .layer.distance import PairwiseDistance
from .utils import weight_norm, remove_weight_norm, spectral_norm

# -- 2.0-beta top-level nn surface tail --------------------------------------
# (parity: python/paddle/nn/__init__.py — the beta exported lowercase-`d`
# layer aliases, 1.8 holdover layers, the control-flow fns, and the layer
# submodules at nn top level)
from .layer import common, conv, norm, rnn, loss  # noqa: F401
from ..nn.functional import extension  # noqa: F401
from ..nn.functional import vision  # noqa: F401
from .layer.common import (Pad1D as ConstantPad1d,  # noqa: F401
                           Pad2D as ConstantPad2d,
                           Pad3D as ConstantPad3d,
                           ZeroPad2D as ZeroPad2d,
                           UpsamplingNearest2D as UpsamplingNearest2d,
                           UpsamplingBilinear2D as UpsamplingBilinear2d)
from ..fluid.layers import (beam_search, beam_search_decode,  # noqa: F401
                            gather_tree, cond, case, switch_case,
                            while_loop, clip_by_norm)
from . import utils as weight_norm_hook  # noqa: F401


def _pad_subclass(base, mode, fmt, name):
    """Mode-fixed pad layer CLASSES (isinstance/subclass must work)."""
    def __init__(self, padding, data_format=None, _name=None):
        base.__init__(self, padding, mode=mode,
                      data_format=data_format or fmt)
    return type(name, (base,), {'__init__': __init__})


ReflectionPad1d = _pad_subclass(Pad1D, 'reflect', 'NCL', 'ReflectionPad1d')
ReflectionPad2d = _pad_subclass(Pad2D, 'reflect', 'NCHW', 'ReflectionPad2d')
ReplicationPad1d = _pad_subclass(Pad1D, 'replicate', 'NCL',
                                 'ReplicationPad1d')
ReplicationPad2d = _pad_subclass(Pad2D, 'replicate', 'NCHW',
                                 'ReplicationPad2d')
ReplicationPad3d = _pad_subclass(Pad3D, 'replicate', 'NCDHW',
                                 'ReplicationPad3d')

# lowercase-d beta aliases
Conv1d, Conv2d, Conv3d = Conv1D, Conv2D, Conv3D
ConvTranspose1d = Conv1DTranspose
ConvTranspose2d = Conv2DTranspose
ConvTranspose3d = Conv3DTranspose
BatchNorm1d, BatchNorm2d, BatchNorm3d = BatchNorm1D, BatchNorm2D, BatchNorm3D
InstanceNorm1d, InstanceNorm2d, InstanceNorm3d = (InstanceNorm1D,
                                                  InstanceNorm2D,
                                                  InstanceNorm3D)
MaxPool1d, MaxPool2d, MaxPool3d = MaxPool1D, MaxPool2D, MaxPool3D
AvgPool1d, AvgPool2d, AvgPool3d = AvgPool1D, AvgPool2D, AvgPool3D
AdaptiveMaxPool1d = AdaptiveMaxPool1D
AdaptiveMaxPool2d = AdaptiveMaxPool2D
AdaptiveMaxPool3d = AdaptiveMaxPool3D
AdaptiveAvgPool1d = AdaptiveAvgPool1D
AdaptiveAvgPool2d = AdaptiveAvgPool2D
AdaptiveAvgPool3d = AdaptiveAvgPool3D
Dropout2d, Dropout3d = Dropout2D, Dropout3D

# 1.8 holdover layers — lazy: fluid.dygraph imports jit which imports nn,
# so a module-level import here would close an import cycle
def __getattr__(name):
    if name in ('BilinearTensorProduct', 'InstanceNorm'):
        from ..fluid import dygraph as _D
        return getattr(_D, name)
    raise AttributeError(f"module 'paddle.nn' has no attribute {name!r}")


class Pool2D(Layer):
    """1.8 dygraph.Pool2D: pool_type/pool_size signature."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, data_format="NCHW"):
        super().__init__()
        self._kw = dict(pool_size=pool_size, pool_type=pool_type,
                        pool_stride=pool_stride, pool_padding=pool_padding,
                        global_pooling=global_pooling, ceil_mode=ceil_mode,
                        exclusive=exclusive, data_format=data_format)

    def forward(self, input):
        from ..fluid.layers import pool2d
        return pool2d(input, **self._kw)


class HSigmoid(Layer):
    """1.8 hierarchical-sigmoid layer over the functional hsigmoid."""

    def __init__(self, feature_size, num_classes, param_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False):
        super().__init__()
        from ..fluid.layers_tail import _op_param
        from .initializer import XavierUniform, Constant
        n_nodes = max(num_classes - 1, 1)
        self.weight = _op_param([n_nodes, feature_size], param_attr,
                                XavierUniform(), 'hsigmoid_w')
        self.bias = _op_param([n_nodes], bias_attr, Constant(0.0),
                              'hsigmoid_b')
        self._num_classes = num_classes
        self._is_custom = is_custom

    def forward(self, input, label, path_table=None, path_code=None):
        # inject this layer's persistent weight/bias by rebuilding the
        # functional loss against them
        import jax.numpy as jnp
        import math as _math
        from ..core.tensor import apply_op
        from ..tensor._helpers import _t
        num_classes = self._num_classes
        n_nodes = max(num_classes - 1, 1)
        depth = max(int(_math.ceil(_math.log2(max(num_classes, 2)))), 1)
        if self._is_custom:
            def fn(xv, lv, wv, bv, ptv, pcv):
                nodes = ptv.astype(jnp.int32)
                codes = pcv.astype(xv.dtype)
                valid = (nodes >= 0)
                nid = jnp.maximum(nodes, 0)
                s = jnp.einsum('bd,bkd->bk', xv, wv[nid]) + bv[nid]
                z = (1.0 - 2.0 * codes) * s
                sp = jnp.maximum(-z, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(z)))
                return jnp.where(valid, sp, 0.0).sum(axis=1, keepdims=True)
            return apply_op(fn, (_t(input), _t(label), self.weight,
                                 self.bias, _t(path_table), _t(path_code)))

        def fn(xv, lv, wv, bv):
            leaf = lv.astype(jnp.int32).reshape(-1) + num_classes
            losses = jnp.zeros((xv.shape[0],), xv.dtype)
            node = leaf
            for _ in range(depth):
                code = (node % 2).astype(xv.dtype)
                parent = node // 2
                valid = parent >= 1
                nid = jnp.clip(parent - 1, 0, n_nodes - 1)
                s = jnp.einsum('bd,bd->b', xv, wv[nid]) + bv[nid]
                z = (1.0 - 2.0 * code) * s
                sp = jnp.maximum(-z, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(z)))
                losses = losses + jnp.where(valid, sp, 0.0)
                node = parent
            return losses[:, None]
        return apply_op(fn, (_t(input), _t(label), self.weight, self.bias))


class RowConv(Layer):
    """1.8 lookahead row convolution layer."""

    def __init__(self, num_channels, future_context_size, param_attr=None,
                 act=None):
        super().__init__()
        from ..fluid.layers_tail import _op_param
        from .initializer import XavierUniform
        self.weight = _op_param([future_context_size + 1, num_channels],
                                param_attr, XavierUniform(), 'row_conv_w')
        self._act = act
        self._k = future_context_size + 1

    def forward(self, input):
        import jax.numpy as jnp
        from ..core.tensor import apply_op
        from ..tensor._helpers import _t
        k = self._k

        def fn(v, wv):
            pad = jnp.pad(v, ((0, 0), (0, k - 1), (0, 0)))
            out = pad[:, 0:v.shape[1], :] * wv[0]
            for i in range(1, k):
                out = out + pad[:, i:i + v.shape[1], :] * wv[i]
            return out

        out = apply_op(fn, (_t(input), self.weight))
        if self._act:
            out = getattr(functional, self._act)(out)
        return out
