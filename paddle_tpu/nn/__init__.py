"""paddle_tpu.nn. Parity: python/paddle/nn/__init__.py."""
from .layer_base import Layer, functional_call, state_values, param_values, \
    buffer_values, load_state_values
from . import functional
from . import initializer
from .initializer import ParamAttr
from .clip import (ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
                   GradientClipByValue, GradientClipByNorm,
                   GradientClipByGlobalNorm, clip_grad_norm_)
from .regularizer import L1Decay, L2Decay

from .layer.container import Sequential, LayerList, ParameterList, LayerDict
from .layer.common import (Identity, Linear, Embedding, Flatten, Dropout,
                           Dropout2D, Dropout3D, AlphaDropout, Upsample,
                           UpsamplingNearest2D, UpsamplingBilinear2D, Pad1D,
                           Pad2D, Pad3D, ZeroPad2D, CosineSimilarity,
                           PixelShuffle, PixelUnshuffle, Bilinear, Unfold, Fold)
from .layer.conv import (Conv1D, Conv2D, Conv3D, Conv1DTranspose,
                         Conv2DTranspose, Conv3DTranspose)
from .layer.pooling import (MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D,
                            AvgPool2D, AvgPool3D, AdaptiveAvgPool1D,
                            AdaptiveAvgPool2D, AdaptiveAvgPool3D,
                            AdaptiveMaxPool1D, AdaptiveMaxPool2D,
                            AdaptiveMaxPool3D)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                         SyncBatchNorm, LayerNorm, RMSNorm, GroupNorm,
                         InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
                         LocalResponseNorm, SpectralNorm)
from .layer.activation import (ReLU, ReLU6, LeakyReLU, PReLU, RReLU, ELU, CELU,
                               GELU, Sigmoid, Hardsigmoid, Hardswish,
                               Hardshrink, Hardtanh, Softplus, Softshrink,
                               Softsign, Swish, Silu, Mish, Tanh, Tanhshrink,
                               ThresholdedReLU, LogSigmoid, LogSoftmax, Softmax,
                               Maxout, GLU, SELU)
from .layer.loss import (CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss,
                         BCEWithLogitsLoss, KLDivLoss, SmoothL1Loss,
                         MarginRankingLoss, CTCLoss, HingeEmbeddingLoss,
                         CosineEmbeddingLoss, TripletMarginLoss)
from .layer.rnn import (RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN,
                        BiRNN, SimpleRNN, LSTM, GRU)
from .decode import (Decoder, BeamSearchDecoder, dynamic_decode, DecodeHelper,
                     TrainingHelper, GreedyEmbeddingHelper,
                     SampleEmbeddingHelper, BasicDecoder)
from .layer.transformer import (MultiHeadAttention, TransformerEncoderLayer,
                                TransformerEncoder, TransformerDecoderLayer,
                                TransformerDecoder, Transformer)
from .layer.distance import PairwiseDistance
from .utils import weight_norm, remove_weight_norm, spectral_norm
