"""Gradient clipping. Parity: python/paddle/fluid/clip.py.

Clippers operate on (param, grad-value) pairs functionally so the optimizer's
jitted update path can apply them inside the compiled step.
"""
import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, params_grads):
        """params_grads: list of (Parameter, grad jax array)."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        return [(p, jnp.clip(g, self.min, self.max) if p.need_clip else g)
                for p, g in params_grads]

    def __repr__(self):
        return f"ClipGradByValue(min={self.min}, max={self.max})"


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if not p.need_clip:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(g * g))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, g * scale))
        return out

    def __repr__(self):
        return f"ClipGradByNorm(clip_norm={self.clip_norm})"


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def __call__(self, params_grads):
        sq = [jnp.sum(g * g) for p, g in params_grads if p.need_clip]
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        return [(p, g * scale if p.need_clip else g) for p, g in params_grads]

    def __repr__(self):
        return f"ClipGradByGlobalNorm(clip_norm={self.clip_norm})"


# fluid-era aliases
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    """torch-style helper used by some reference scripts."""
    from ..core.tensor import Tensor
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float('inf'):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._value) ** norm_type) for g in grads])) ** (
            1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._inplace_value(p.grad._value * scale)
    return Tensor(total)
