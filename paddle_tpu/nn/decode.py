"""Decoding stack: dynamic_decode + BeamSearchDecoder + decode helpers.

Parity: /root/reference/python/paddle/fluid/layers/rnn.py:743 (Decoder),
:856 (BeamSearchDecoder), :1327 (dynamic_decode), :1557 (DecodeHelper,
TrainingHelper, GreedyEmbeddingHelper, SampleEmbeddingHelper), :1876
(BasicDecoder).

TPU-first design: the decode loop runs over PREALLOCATED fixed-shape output
buffers written with ``lax.dynamic_update_index_in_dim`` — no growing arrays,
so the whole loop lowers to one ``lax.while_loop`` under jit (static
``max_step_num`` bound, early exit when every sequence is finished). The same
code path runs eagerly as a python loop (see fluid.layers.while_loop).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..core import autograd

__all__ = ['Decoder', 'BeamSearchDecoder', 'dynamic_decode', 'DecodeHelper',
           'TrainingHelper', 'GreedyEmbeddingHelper', 'SampleEmbeddingHelper',
           'BasicDecoder', 'beam_search', 'beam_search_decode']

_KINF = 1e9


def _t(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _map_structure(fn, *structs):
    """Apply fn over parallel nested structures (Tensor leaves)."""
    return jax.tree_util.tree_map(
        fn, *structs, is_leaf=lambda x: isinstance(x, Tensor))


def _flatten(struct):
    return jax.tree_util.tree_leaves(
        struct, is_leaf=lambda x: isinstance(x, Tensor))


class Decoder:
    """Abstract decoder interface: initialize / step / finalize.

    Parity: reference rnn.py:743.
    """

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam-search decoder wrapping a cell (parity: reference rnn.py:856).

    The cell's inputs/states are tiled to ``[batch_size * beam_size, ...]``;
    tensors used inside ``cell.forward`` that are batch-major must be tiled
    with :meth:`tile_beam_merge_with_batch` by the caller (e.g. attention
    encoder output).
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)

    # -- shape utilities ----------------------------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B * beam, ...] with each row repeated beam times."""
        x = _t(x)
        return apply_op(
            lambda v: jnp.repeat(v, beam_size, axis=0), (x,))

    def _split_batch_beams(self, x):
        x = _t(x)
        W = self.beam_size
        return apply_op(
            lambda v: v.reshape((v.shape[0] // W, W) + v.shape[1:]), (x,))

    def _merge_batch_beams(self, x):
        x = _t(x)
        return apply_op(
            lambda v: v.reshape((v.shape[0] * v.shape[1],) + v.shape[2:]),
            (x,))

    def _expand_to_beam_size(self, x):
        """[B, ...] -> [B, beam, ...]."""
        x = _t(x)
        return apply_op(
            lambda v: jnp.broadcast_to(
                v[:, None], (v.shape[0], self.beam_size) + v.shape[1:]), (x,))

    def _gather(self, x, indices):
        """Gather beams: x [B, W, ...], indices [B, W] -> x[b, indices[b, w]]."""
        def fn(v, idx):
            ii = idx.reshape(idx.shape + (1,) * (v.ndim - 2)).astype(jnp.int32)
            return jnp.take_along_axis(v, ii, axis=1)
        return apply_op(fn, (_t(x), _t(indices)))

    # -- decoder interface --------------------------------------------------
    def initialize(self, initial_cell_states):
        state0 = _flatten(initial_cell_states)[0]
        batch = state0.shape[0]
        W = self.beam_size
        cell_states = _map_structure(self._expand_to_beam_size,
                                     initial_cell_states)
        init_ids = Tensor(jnp.full((batch, W), self.start_token, jnp.int32))
        log_probs = Tensor(jnp.broadcast_to(
            jnp.array([[0.] + [-_KINF] * (W - 1)], jnp.float32), (batch, W)))
        finished = Tensor(jnp.zeros((batch, W), jnp.bool_))
        lengths = Tensor(jnp.zeros((batch, W), jnp.int32))
        inputs = (self.embedding_fn(init_ids) if self.embedding_fn
                  else init_ids)
        states = {'cell_states': cell_states, 'log_probs': log_probs,
                  'finished': finished, 'lengths': lengths}
        return inputs, states, finished

    def _beam_search_step(self, time, logits, next_cell_states, beam_state):
        W = self.beam_size
        vocab = logits.shape[-1]

        def fn(lg, prev_lp, prev_fin, prev_len):
            step_lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
            noend = jnp.full((vocab,), -_KINF,
                             jnp.float32).at[self.end_token].set(0.)
            step_lp = jnp.where(prev_fin[..., None], noend, step_lp)
            lp = step_lp + prev_lp[..., None]               # (B, W, V)
            flat = lp.reshape(lp.shape[0], W * vocab)
            topk_scores, topk_idx = jax.lax.top_k(flat, W)  # (B, W)
            beam_idx = (topk_idx // vocab).astype(jnp.int32)
            token_idx = (topk_idx % vocab).astype(jnp.int32)
            nxt_fin = jnp.take_along_axis(prev_fin, beam_idx, axis=1)
            nxt_len = jnp.take_along_axis(prev_len, beam_idx, axis=1)
            nxt_len = nxt_len + (~nxt_fin).astype(jnp.int32)
            nxt_fin = nxt_fin | (token_idx == self.end_token)
            return (topk_scores, token_idx, beam_idx, topk_scores,
                    nxt_fin, nxt_len)

        (scores, token_idx, beam_idx, next_lp, next_fin,
         next_len) = apply_op(
            fn, (logits, beam_state['log_probs'], beam_state['finished'],
                 beam_state['lengths']), n_outputs=6, differentiable=False)
        next_cell_states = _map_structure(
            lambda x: self._gather(x, beam_idx), next_cell_states)
        output = {'scores': scores, 'predicted_ids': token_idx,
                  'parent_ids': beam_idx}
        state = {'cell_states': next_cell_states, 'log_probs': next_lp,
                 'finished': next_fin, 'lengths': next_len}
        return output, state

    def step(self, time, inputs, states, **kwargs):
        inputs = _map_structure(self._merge_batch_beams, inputs)
        cell_states = _map_structure(self._merge_batch_beams,
                                     states['cell_states'])
        cell_outputs, next_cell_states = self.cell(inputs, cell_states,
                                                   **kwargs)
        cell_outputs = _map_structure(self._split_batch_beams, cell_outputs)
        next_cell_states = _map_structure(self._split_batch_beams,
                                          next_cell_states)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        output, state = self._beam_search_step(
            time, cell_outputs, next_cell_states, states)
        finished = state['finished']
        sample_ids = output['predicted_ids']
        next_inputs = (self.embedding_fn(sample_ids) if self.embedding_fn
                       else sample_ids)
        return output, state, next_inputs, finished

    def pad_buffers(self, buffers, t_final):
        """Fill unwritten slots after an early loop exit (t >= t_final):
        predicted_ids -> end_token, parent_ids -> identity, so gather_tree's
        backtrace passes through them unchanged."""
        W = self.beam_size
        end = self.end_token

        def pad(name, b):
            def fn(v, tf):
                written = (jnp.arange(v.shape[0]) < tf).reshape(
                    (-1,) + (1,) * (v.ndim - 1))
                if name == 'predicted_ids':
                    fill = jnp.full_like(v, end)
                else:  # parent_ids: identity backtrace
                    fill = jnp.broadcast_to(
                        jnp.arange(W, dtype=v.dtype), v.shape)
                return jnp.where(written, v, fill)
            return apply_op(fn, (_t(b), _t(t_final)), differentiable=False)

        out = dict(buffers)
        out['predicted_ids'] = pad('predicted_ids', buffers['predicted_ids'])
        out['parent_ids'] = pad('parent_ids', buffers['parent_ids'])
        return out

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrace the beam tree; outputs are time-major [T, B, W]."""
        from ..nn.functional.extension import gather_tree
        predicted_ids = gather_tree(outputs['predicted_ids'],
                                    outputs['parent_ids'])
        return predicted_ids, final_states

    @property
    def tracks_own_finished(self):
        return True


def _write_at(buf, t, val):
    """Write val into time-major buffer buf at index t (jit-safe)."""
    def fn(b, tt, v):
        return jax.lax.dynamic_update_index_in_dim(
            b, v.astype(b.dtype), tt.astype(jnp.int32), 0)
    return apply_op(fn, (buf, t, val))


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run ``decoder`` until every sequence finishes or ``max_step_num``.

    Parity: reference rnn.py:1327. TPU-first: one fused loop over
    preallocated [max_T, B, ...] buffers; under jit this is a single
    ``lax.while_loop``. ``max_step_num`` must be a static python int
    (defaults to 256 — XLA needs a static bound; documented divergence).
    """
    from ..fluid.layers import while_loop
    max_T = int(max_step_num) if max_step_num is not None else 256

    import contextlib
    grad_ctx = autograd.no_grad if is_test else contextlib.nullcontext
    with grad_ctx():
        initial_inputs, initial_states, initial_finished = decoder.initialize(
            inits)

        # Probe step at t=0 to learn output structure (shapes/dtypes), then
        # allocate the full time-major buffers.
        outputs0, states0, next_inputs0, finished0 = decoder.step(
            Tensor(jnp.asarray(0, jnp.int32)), initial_inputs, initial_states,
            **kwargs)
    if not decoder.tracks_own_finished:
        finished0 = apply_op(lambda a, b: a | b,
                             (_t(initial_finished), _t(finished0)),
                             differentiable=False)
    seq_len0 = apply_op(
        lambda fin: (~fin).astype(jnp.int32), (_t(initial_finished),),
        differentiable=False)

    def alloc(o):
        return Tensor(jnp.zeros((max_T,) + tuple(o.shape),
                                o._value.dtype))
    buffers = _map_structure(alloc, outputs0)
    buffers = _map_structure(
        lambda b, o: _write_at(b, Tensor(jnp.asarray(0, jnp.int32)), o),
        buffers, outputs0)

    def cond_fn(t, inputs, states, finished, seq_len, buffers):
        return apply_op(
            lambda tt, fin: (tt < max_T) & ~jnp.all(fin),
            (t, _t(finished)), differentiable=False)

    def body_fn(t, inputs, states, finished, seq_len, buffers):
        outputs, next_states, next_inputs, next_finished = decoder.step(
            t, inputs, states, **kwargs)
        if not decoder.tracks_own_finished:
            next_finished = apply_op(lambda a, b: a | b,
                                     (_t(finished), _t(next_finished)),
                                     differentiable=False)
        next_seq_len = apply_op(
            lambda sl, fin: sl + (~fin).astype(jnp.int32),
            (_t(seq_len), _t(finished)), differentiable=False)
        if impute_finished:
            next_states = _map_structure(
                lambda old, new: apply_op(
                    lambda o, n, fin: jnp.where(
                        fin.reshape(fin.shape + (1,) * (n.ndim - fin.ndim)),
                        o.astype(n.dtype), n),
                    (_t(old), _t(new), _t(finished))),
                states, next_states)
        buffers_new = _map_structure(
            lambda b, o: _write_at(b, t, o), buffers, outputs)
        t_next = apply_op(lambda tt: tt + 1, (t,), differentiable=False)
        return (t_next, next_inputs, next_states, next_finished,
                next_seq_len, buffers_new)

    loop_vars = (Tensor(jnp.asarray(1, jnp.int32)), next_inputs0, states0,
                 finished0, seq_len0, buffers)
    with grad_ctx():
        (t_final, _, final_states, final_finished, seq_len,
         buffers) = while_loop(cond_fn, body_fn, list(loop_vars))

    if decoder.tracks_own_finished and isinstance(final_states, dict) \
            and 'lengths' in final_states:
        seq_len = final_states['lengths']

    if hasattr(decoder, 'pad_buffers'):
        buffers = decoder.pad_buffers(buffers, t_final)
    try:
        final_outputs, final_states = decoder.finalize(
            buffers, final_states, seq_len)
    except NotImplementedError:
        final_outputs = buffers

    if not output_time_major:
        final_outputs = _map_structure(
            lambda x: apply_op(lambda v: jnp.swapaxes(v, 0, 1), (_t(x),),
                               differentiable=False),
            final_outputs)

    if return_length:
        return final_outputs, final_states, seq_len
    return final_outputs, final_states


# -- helper-based decoding (parity: reference rnn.py:1557-2036) -------------

class DecodeHelper:
    """Interface: initialize / sample / next_inputs."""

    def initialize(self):
        raise NotImplementedError

    def sample(self, time, outputs, states):
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """Teacher-forcing helper: slices the next ground-truth input each step.

    Parity: reference rnn.py:1626.
    """

    def __init__(self, inputs, sequence_length, time_major=False):
        self.inputs = _t(inputs)
        self.sequence_length = _t(sequence_length)
        self.time_major = time_major
        self._max_t = (self.inputs.shape[0] if time_major
                       else self.inputs.shape[1])

    def initialize(self):
        init_finished = apply_op(
            lambda sl: sl <= 0, (self.sequence_length,),
            differentiable=False)
        init_inputs = apply_op(
            lambda x: (x[0] if self.time_major else x[:, 0]), (self.inputs,))
        return init_inputs, init_finished

    def sample(self, time, outputs, states):
        return apply_op(lambda o: jnp.argmax(o, axis=-1).astype(jnp.int32),
                        (_t(outputs),), differentiable=False)

    def next_inputs(self, time, outputs, states, sample_ids):
        axis = 0 if self.time_major else 1
        max_t = self._max_t

        def fin_fn(tt, sl):
            return (tt + 1) >= jnp.minimum(sl, max_t)

        def in_fn(x, tt):
            nxt = jnp.minimum(tt + 1, max_t - 1).astype(jnp.int32)
            sl = jax.lax.dynamic_index_in_dim(x, nxt, axis, keepdims=False)
            return sl
        finished = apply_op(fin_fn, (_t(time), self.sequence_length),
                            differentiable=False)
        next_in = apply_op(in_fn, (self.inputs, _t(time)))
        return finished, next_in, states


class GreedyEmbeddingHelper(DecodeHelper):
    """Greedy argmax sampling + embedding lookup (reference rnn.py:1779)."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = _t(np.asarray(start_tokens, np.int32))
        self.end_token = int(end_token)

    def initialize(self):
        batch = self.start_tokens.shape[0]
        init_finished = Tensor(jnp.zeros((batch,), jnp.bool_))
        return self.embedding_fn(self.start_tokens), init_finished

    def sample(self, time, outputs, states):
        return apply_op(lambda o: jnp.argmax(o, axis=-1).astype(jnp.int32),
                        (_t(outputs),), differentiable=False)

    def next_inputs(self, time, outputs, states, sample_ids):
        finished = apply_op(lambda s: s == self.end_token, (_t(sample_ids),),
                            differentiable=False)
        return finished, self.embedding_fn(sample_ids), states


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Multinomial sampling helper (reference rnn.py:1876)."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.temperature = softmax_temperature
        from ..core import rng
        self._key = rng._make_key(seed) if seed is not None else rng.next_key()

    def sample(self, time, outputs, states):
        temp = self.temperature

        def fn(o, tt):
            logits = o if temp is None else o / temp
            key = jax.random.fold_in(self._key, tt.astype(jnp.int32))
            return jax.random.categorical(key, logits, axis=-1).astype(
                jnp.int32)
        return apply_op(fn, (_t(outputs), _t(time)), differentiable=False)


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, return_parent_idx=False):
    """One beam-search step (parity: reference rnn.py:3040 beam_search op).

    Dense TPU redesign of the LoD-based op: inputs are batch-major dense
    tensors — pre_ids/pre_scores (B, W), scores (B, W, V) — instead of LoD
    levels. Returns (selected_ids, selected_scores[, parent_idx]) each
    (B, W). Finished beams (pre_ids == end_id) propagate end_id with their
    frozen score, matching the reference's finished-branch handling.
    """
    pre_ids, pre_scores = _t(pre_ids), _t(pre_scores)
    scores = _t(scores)
    W, end = int(beam_size), int(end_id)

    def fn(pids, pscores, sc):
        sc = sc.astype(jnp.float32)
        if not is_accumulated:
            sc = jnp.log(sc) + pscores[..., None]
        finished = pids == end
        vocab = sc.shape[-1]
        noend = jnp.full((vocab,), -_KINF, jnp.float32).at[end].set(0.)
        sc = jnp.where(finished[..., None], noend + pscores[..., None], sc)
        flat = sc.reshape(sc.shape[0], W * vocab)
        top_sc, top_idx = jax.lax.top_k(flat, W)
        parent = (top_idx // vocab).astype(jnp.int32)
        token = (top_idx % vocab).astype(jnp.int32)
        return token, top_sc, parent

    token, top_sc, parent = apply_op(fn, (pre_ids, pre_scores, scores),
                                     n_outputs=3, differentiable=False)
    if return_parent_idx:
        return token, top_sc, parent
    return token, top_sc


def beam_search_decode(ids, scores, beam_size, end_id):
    """Backtrace full sequences from per-step beam outputs (parity:
    reference rnn.py:3200 beam_search_decode op; dense analogue).

    ids/scores: time-major (T, B, W) stacks of per-step (token, parent)
    pairs is the LoD-free input here — pass ids=(token_ids, parent_ids).
    Returns (sequences, sequence_scores) with sequences (T, B, W).
    """
    token_ids, parent_ids = ids
    from ..nn.functional.extension import gather_tree
    seqs = gather_tree(_t(token_ids), _t(parent_ids))
    return seqs, _t(scores)


class BasicDecoder(Decoder):
    """cell + helper + optional output layer (reference rnn.py:1942)."""

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        initial_inputs, initial_finished = self.helper.initialize()
        return initial_inputs, initial_cell_states, initial_finished

    def step(self, time, inputs, states, **kwargs):
        cell_outputs, cell_states = self.cell(inputs, states, **kwargs)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        sample_ids = self.helper.sample(time, cell_outputs, cell_states)
        finished, next_inputs, next_states = self.helper.next_inputs(
            time, cell_outputs, cell_states, sample_ids)
        outputs = {'cell_outputs': cell_outputs, 'sample_ids': sample_ids}
        return outputs, next_states, next_inputs, finished

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError
