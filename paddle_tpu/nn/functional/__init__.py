"""nn.functional namespace. Parity: python/paddle/nn/functional/__init__.py."""
from .activation import *  # noqa
from .common import *  # noqa
from .conv import *  # noqa
from .pooling import *  # noqa
from .norm import *  # noqa
from .loss import *  # noqa
from .extension import *  # noqa
from .vision import *  # noqa
from .transformer import scaled_dot_product_attention, multi_head_attention  # noqa
from .rnn import rnn_scan  # noqa
from .crf import linear_chain_crf, crf_decoding  # noqa

# -- 2.0-beta DEFINE_ALIAS tail -------------------------------------------
# The reference's paddle.nn.functional re-exports the fluid-era op zoo
# wholesale (python/paddle/nn/functional/__init__.py, the DEFINE_ALIAS
# block). Those ops live in paddle_tpu.fluid.layers; resolving lazily via
# PEP 562 keeps nn.functional importable without the fluid package
# (fluid imports nn, so an eager import here would be a cycle).
_FLUID_ALIASES = frozenset([
    'adaptive_pool2d', 'adaptive_pool3d', 'add_position_encoding',
    'affine_channel', 'anchor_generator', 'assign', 'bipartite_match',
    'birnn', 'box_clip', 'box_coder', 'box_decoder_and_assign', 'bpr_loss',
    'center_loss', 'collect_fpn_proposals', 'continuous_value_model',
    'cosine_decay', 'deformable_roi_pooling', 'density_prior_box',
    'detection_output', 'dice_loss', 'distribute_fpn_proposals',
    'edit_distance', 'erf', 'exponential_decay', 'filter_by_instag',
    'fsp_matrix', 'generate_mask_labels', 'generate_proposal_labels',
    'generate_proposals', 'grid_sampler', 'hard_sigmoid', 'hard_swish',
    'hash', 'hsigmoid',
    'image_resize', 'image_resize_short', 'inverse_time_decay',
    'iou_similarity', 'l2_normalize', 'linear_lr_warmup', 'lrn',
    'multiclass_nms', 'natural_exp_decay', 'noam_decay', 'pad2d',
    'pad_constant_like', 'piecewise_decay', 'polygon_box_transform',
    'polynomial_decay', 'pool2d', 'pool3d', 'prior_box', 'prroi_pool',
    'psroi_pool', 'random_crop', 'rank_loss', 'resize_bilinear',
    'resize_nearest', 'resize_trilinear', 'retinanet_detection_output',
    'retinanet_target_assign', 'roi_align', 'roi_perspective_transform',
    'roi_pool', 'row_conv', 'rpn_target_assign', 'shuffle_channel',
    'sigmoid_cross_entropy_with_logits', 'similarity_focus', 'smooth_l1',
    'space_to_depth', 'ssd_loss', 'target_assign',
    'teacher_student_sigmoid_loss', 'warpctc', 'yolo_box', 'yolov3_loss',
])
# the targets are already eager (from .conv import * above): plain bindings
conv_transpose1d = conv1d_transpose  # noqa: F405
conv_transpose2d = conv2d_transpose  # noqa: F405
conv_transpose3d = conv3d_transpose  # noqa: F405

# __all__ makes the lazy names reachable by star-import (which getattr()s
# each listed name, firing __getattr__) and __dir__ keeps dir()/completion
# honest about them
__all__ = sorted(
    [n for n in globals() if not n.startswith('_')] + list(_FLUID_ALIASES))


def __getattr__(name):
    if name in _FLUID_ALIASES:
        from ...fluid import layers as _fluid_layers
        return getattr(_fluid_layers, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return __all__
