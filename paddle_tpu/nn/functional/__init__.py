"""nn.functional namespace. Parity: python/paddle/nn/functional/__init__.py."""
from .activation import *  # noqa
from .common import *  # noqa
from .conv import *  # noqa
from .pooling import *  # noqa
from .norm import *  # noqa
from .loss import *  # noqa
from .extension import *  # noqa
from .vision import *  # noqa
from .transformer import scaled_dot_product_attention, multi_head_attention  # noqa
from .rnn import rnn_scan  # noqa
from .crf import linear_chain_crf, crf_decoding  # noqa
