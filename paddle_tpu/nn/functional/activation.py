"""Activation functionals. Parity: python/paddle/nn/functional/activation.py."""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, apply_op
from ...tensor._helpers import _t

__all__ = ['relu', 'relu6', 'leaky_relu', 'prelu', 'elu', 'selu', 'gelu',
           'sigmoid', 'hardsigmoid', 'hardswish', 'hardshrink', 'hardtanh',
           'softshrink', 'tanhshrink', 'softplus', 'softsign', 'swish', 'silu',
           'mish', 'maxout', 'log_sigmoid', 'log_softmax', 'softmax', 'tanh',
           'thresholded_relu', 'glu', 'celu', 'rrelu', 'logsigmoid',
           'soft_relu', 'brelu']


def relu(x, name=None):
    return apply_op(jax.nn.relu, (_t(x),))


def relu6(x, name=None):
    return apply_op(lambda v: jnp.minimum(jnp.maximum(v, 0), 6), (_t(x),))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op(lambda v: jnp.where(v >= 0, v, negative_slope * v), (_t(x),))


def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = _t(x), _t(weight)
    def fn(v, w):
        if w.size > 1:
            shp = [1] * v.ndim
            ch_axis = 1 if data_format[1] == 'C' else v.ndim - 1
            shp[ch_axis] = w.size
            w = w.reshape(shp)
        return jnp.where(v >= 0, v, w * v)
    return apply_op(fn, (x, weight))


def elu(x, alpha=1.0, name=None):
    return apply_op(lambda v: jax.nn.elu(v, alpha), (_t(x),))


def celu(x, alpha=1.0, name=None):
    return apply_op(lambda v: jax.nn.celu(v, alpha), (_t(x),))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)),
                    (_t(x),))


def gelu(x, approximate=False, name=None):
    return apply_op(lambda v: jax.nn.gelu(v, approximate=approximate), (_t(x),))


def sigmoid(x, name=None):
    return apply_op(jax.nn.sigmoid, (_t(x),))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op(lambda v: jnp.clip(slope * v + offset, 0., 1.), (_t(x),))


def hardswish(x, name=None):
    return apply_op(lambda v: v * jnp.clip(v + 3., 0., 6.) / 6., (_t(x),))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op(lambda v: jnp.clip(v, min, max), (_t(x),))


brelu = hardtanh


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.), (_t(x),))


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda v: jnp.where(v > threshold, v - threshold,
                            jnp.where(v < -threshold, v + threshold, 0.)), (_t(x),))


def tanhshrink(x, name=None):
    return apply_op(lambda v: v - jnp.tanh(v), (_t(x),))


def softplus(x, beta=1, threshold=20, name=None):
    return apply_op(
        lambda v: jnp.where(beta * v > threshold, v,
                            jnp.log1p(jnp.exp(beta * v)) / beta), (_t(x),))


def soft_relu(x, threshold=40.0, name=None):
    return apply_op(lambda v: jnp.log1p(jnp.exp(jnp.clip(v, -threshold, threshold))),
                    (_t(x),))


def softsign(x, name=None):
    return apply_op(lambda v: v / (1 + jnp.abs(v)), (_t(x),))


def swish(x, name=None):
    return apply_op(lambda v: v * jax.nn.sigmoid(v), (_t(x),))


silu = swish


def mish(x, name=None):
    return apply_op(lambda v: v * jnp.tanh(jax.nn.softplus(v)), (_t(x),))


def maxout(x, groups, axis=1, name=None):
    x = _t(x)
    def fn(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        shp = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(shp), axis=ax + 1)
    return apply_op(fn, (x,))


def log_sigmoid(x, name=None):
    return apply_op(jax.nn.log_sigmoid, (_t(x),))


logsigmoid = log_sigmoid


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = _t(x)
    if dtype is not None:
        x = x.astype(dtype)
    return apply_op(lambda v: jax.nn.log_softmax(v, axis=axis), (x,))


def softmax(x, axis=-1, dtype=None, name=None):
    x = _t(x)
    if dtype is not None:
        x = x.astype(dtype)
    return apply_op(lambda v: jax.nn.softmax(v, axis=axis), (x,))


def tanh(x, name=None):
    return apply_op(jnp.tanh, (_t(x),))


def thresholded_relu(x, threshold=1.0, name=None):
    return apply_op(lambda v: jnp.where(v > threshold, v, 0.), (_t(x),))


def glu(x, axis=-1, name=None):
    def fn(v):
        a, b = jnp.split(v, 2, axis=axis)
        return a * jax.nn.sigmoid(b)
    return apply_op(fn, (_t(x),))


def rrelu(x, lower=1. / 8., upper=1. / 3., training=True, name=None):
    x = _t(x)
    if training:
        from ...core import rng as _rng
        key = _rng.next_key()
        def fn(v):
            a = jax.random.uniform(key, v.shape, dtype=v.dtype,
                                   minval=lower, maxval=upper)
            return jnp.where(v >= 0, v, a * v)
        return apply_op(fn, (x,))
    mid = (lower + upper) / 2.
    return apply_op(lambda v: jnp.where(v >= 0, v, mid * v), (x,))
