"""Common functionals: linear/embedding/dropout/pad/interpolate/...

Parity: python/paddle/nn/functional/common.py + input.py.
"""
import numbers
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, apply_op
from ...core import rng as _rng
from ...core.dtypes import convert_dtype
from ...tensor._helpers import _t

__all__ = ['linear', 'embedding', 'one_hot', 'label_smooth', 'dropout',
           'dropout2d', 'dropout3d', 'alpha_dropout', 'pad', 'zeropad2d',
           'interpolate', 'upsample', 'bilinear', 'cosine_similarity',
           'pixel_shuffle', 'pixel_unshuffle', 'unfold', 'fold', 'class_center_sample']


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b, W shape (in, out) — parity: F.linear.

    Under amp.auto_cast, x/W are cast to the amp dtype (bf16 on TPU) so the
    matmul hits the MXU at low precision while the bias add stays fused.
    """
    from ...amp import maybe_cast_for

    def mm(v, w, *b):
        v, w = maybe_cast_for('matmul', v, w)
        out = jnp.matmul(v, w)
        if b:
            out = out + b[0].astype(out.dtype)
        return out
    if bias is None:
        return apply_op(mm, (_t(x), _t(weight)))
    return apply_op(mm, (_t(x), _t(weight), _t(bias)))


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Gather rows; padding_idx rows get zero gradient (zeroed lookup).

    TPU-first: 'sparse' grads become dense gathers — XLA scatter-add handles
    the backward; sharded vocab lives in distributed.sharded_embedding.
    """
    x, weight = _t(x), _t(weight)
    def fn(i, w):
        out = jnp.take(w, i, axis=0)
        if padding_idx is not None:
            mask = (i == padding_idx)[..., None]
            out = jnp.where(mask, jnp.zeros_like(out), out)
        return out
    return apply_op(fn, (x, weight))


def one_hot(x, num_classes, name=None):
    x = _t(x)
    return apply_op(lambda i: jax.nn.one_hot(i, num_classes, dtype=jnp.float32),
                    (x,), differentiable=False)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = _t(label)
    if prior_dist is not None:
        return apply_op(lambda l, p: (1 - epsilon) * l + epsilon * p,
                        (label, _t(prior_dist)))
    def fn(l):
        k = l.shape[-1]
        return (1 - epsilon) * l + epsilon / k
    return apply_op(fn, (label,))


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = _t(x)
    if not training or p == 0:
        if mode == "downscale_in_infer" and not training:
            return apply_op(lambda v: v * (1 - p), (x,))
        return x
    if p == 1:
        return apply_op(lambda v: jnp.zeros_like(v), (x,))
    key = _rng.next_key()
    axes = None
    if axis is not None:
        axes = [axis] if isinstance(axis, numbers.Integral) else list(axis)
    def fn(v):
        if axes is None:
            shape = v.shape
        else:
            shape = tuple(v.shape[i] if i in axes else 1 for i in range(v.ndim))
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), jnp.zeros_like(v))
        return jnp.where(keep, v, jnp.zeros_like(v))

    # test-mode variant for Program.clone(for_test=True)
    if mode == "upscale_in_train":
        eval_fn = lambda v: v  # noqa: E731
    else:
        eval_fn = lambda v: v * (1 - p)  # noqa: E731
    return apply_op(fn, (x,), eval_fn=eval_fn)


def dropout2d(x, p=0.5, training=True, data_format='NCHW', name=None):
    axis = [0, 1] if data_format == 'NCHW' else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format='NCDHW', name=None):
    axis = [0, 1] if data_format == 'NCDHW' else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = _t(x)
    if not training or p == 0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    a = ((1 - p) * (1 + p * alpha_p ** 2)) ** -0.5
    b = -a * alpha_p * p
    key = _rng.next_key()
    def fn(v):
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        return a * jnp.where(keep, v, alpha_p) + b
    return apply_op(fn, (x,))


def _pad_pairs(pad, ndim, data_format):
    """Convert paddle pad spec (last-dim-first pairs) to jnp.pad pairs."""
    if len(pad) == 2 * ndim:
        pairs = [(int(pad[2 * i]), int(pad[2 * i + 1])) for i in range(ndim)]
        return pairs
    n_spatial = len(pad) // 2
    pairs_spatial = [(int(pad[2 * i]), int(pad[2 * i + 1]))
                     for i in range(n_spatial)]
    pairs = [(0, 0)] * ndim
    if data_format.startswith('NC'):
        for i, pr in enumerate(pairs_spatial):
            pairs[ndim - 1 - i] = pr
    else:  # NHWC-style: spatial dims are 1..ndim-2
        for i, pr in enumerate(pairs_spatial):
            pairs[ndim - 2 - i] = pr
    return pairs


def pad(x, pad, mode='constant', value=0.0, data_format="NCHW", name=None):
    x = _t(x)
    if isinstance(pad, Tensor):
        pad = pad.numpy().tolist()
    pad = list(pad)
    nd = x.ndim
    pairs = _pad_pairs(pad, nd, data_format)
    jmode = {'constant': 'constant', 'reflect': 'reflect', 'replicate': 'edge',
             'edge': 'edge', 'circular': 'wrap'}[mode]
    def fn(v):
        if jmode == 'constant':
            return jnp.pad(v, pairs, mode='constant', constant_values=value)
        return jnp.pad(v, pairs, mode=jmode)
    return apply_op(fn, (x,))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode='constant', value=0.0, data_format=data_format)


def _resize_axis_coords(out_size, in_size, align_corners, align_mode, scale=None):
    if align_corners:
        if out_size == 1:
            return jnp.zeros((1,))
        return jnp.arange(out_size) * ((in_size - 1) / (out_size - 1))
    ratio = (in_size / out_size) if scale is None else (1.0 / scale)
    if align_mode == 0:
        return jnp.maximum((jnp.arange(out_size) + 0.5) * ratio - 0.5, 0)
    return jnp.arange(out_size) * ratio


def interpolate(x, size=None, scale_factor=None, mode='nearest',
                align_corners=False, align_mode=0, data_format='NCHW', name=None):
    """Parity: F.interpolate (nearest/bilinear/bicubic/trilinear/area/linear)."""
    x = _t(x)
    nd = x.ndim
    channel_last = not data_format.startswith('NC')
    spatial_axes = list(range(1, nd - 1)) if channel_last else list(range(2, nd))

    in_sizes = [x.shape[a] for a in spatial_axes]
    if size is not None:
        if isinstance(size, Tensor):
            size = size.numpy().tolist()
        out_sizes = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in
                     (size if isinstance(size, (list, tuple)) else [size])]
        scales = [None] * len(out_sizes)
    else:
        if isinstance(scale_factor, (int, float)):
            scale_factor = [scale_factor] * len(in_sizes)
        out_sizes = [int(s * f) for s, f in zip(in_sizes, scale_factor)]
        scales = list(scale_factor)

    method = {'nearest': 'nearest', 'bilinear': 'linear', 'linear': 'linear',
              'trilinear': 'linear', 'bicubic': 'cubic', 'area': 'linear'}[mode]

    if method == 'nearest' or (not align_corners and align_mode == 1 and
                               method == 'linear' and False):
        def fn(v):
            out = v
            for ax, (osz, isz) in zip(spatial_axes, zip(out_sizes, in_sizes)):
                idx = jnp.clip(jnp.floor(jnp.arange(osz) * (isz / osz)), 0,
                               isz - 1).astype(jnp.int32)
                out = jnp.take(out, idx, axis=ax)
            return out
        return apply_op(fn, (x,))

    if method == 'cubic':
        def fn(v):
            shape = list(v.shape)
            for a, s in zip(spatial_axes, out_sizes):
                shape[a] = s
            return jax.image.resize(v, shape, method='cubic')
        return apply_op(fn, (x,))

    # linear/bilinear/trilinear with paddle's align semantics via gather+lerp
    def fn(v):
        out = v
        for ax, (osz, isz, sc) in zip(spatial_axes,
                                      zip(out_sizes, in_sizes, scales)):
            coords = _resize_axis_coords(osz, isz, align_corners, align_mode, sc)
            lo = jnp.clip(jnp.floor(coords).astype(jnp.int32), 0, isz - 1)
            hi = jnp.clip(lo + 1, 0, isz - 1)
            w = (coords - lo).astype(v.dtype)
            shape_w = [1] * out.ndim
            shape_w[ax] = osz
            w = w.reshape(shape_w)
            out = (1 - w) * jnp.take(out, lo, axis=ax) + w * jnp.take(out, hi, axis=ax)
        return out
    return apply_op(fn, (x,))


def upsample(x, size=None, scale_factor=None, mode='nearest', align_corners=False,
             align_mode=0, data_format='NCHW', name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    """y_k = x1 W_k x2^T (+ b). weight: (out, in1, in2)."""
    if bias is None:
        return apply_op(lambda a, b, w: jnp.einsum('bi,oij,bj->bo', a, w, b),
                        (_t(x1), _t(x2), _t(weight)))
    return apply_op(lambda a, b, w, bb: jnp.einsum('bi,oij,bj->bo', a, w, b) + bb,
                    (_t(x1), _t(x2), _t(weight), _t(bias)))


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply_op(fn, (_t(x1), _t(x2)))


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = _t(x)
    r = upscale_factor
    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))
    return apply_op(fn, (x,))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = _t(x)
    r = downscale_factor
    def fn(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h // r, w // r, c * r * r)
    return apply_op(fn, (x,))


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col. x: (N, C, H, W) -> (N, C*kh*kw, L)."""
    x = _t(x)
    def norm2(v):
        return [v, v] if isinstance(v, int) else list(v)
    kh, kw = norm2(kernel_sizes)
    sh, sw = norm2(strides)
    dh, dw = norm2(dilations)
    p = norm2(paddings)
    if len(p) == 2:
        pt, pb, pl, pr = p[0], p[0], p[1], p[1]
    else:
        pt, pb, pl, pr = p
    def fn(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pt, pb), (pl, pr)])
        hh, ww = v.shape[2], v.shape[3]
        oh = (hh - (dh * (kh - 1) + 1)) // sh + 1
        ow = (ww - (dw * (kw - 1) + 1)) // sw + 1
        patches = lax.conv_general_dilated_patches(
            v, (kh, kw), (sh, sw), 'VALID', rhs_dilation=(dh, dw),
            dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
        return patches.reshape(n, c * kh * kw, oh * ow)
    return apply_op(fn, (x,))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """col2im — inverse of unfold via scatter-add."""
    x = _t(x)
    def norm2(v):
        return [v, v] if isinstance(v, int) else list(v)
    oh, ow = norm2(output_sizes)
    kh, kw = norm2(kernel_sizes)
    sh, sw = norm2(strides)
    dh, dw = norm2(dilations)
    p = norm2(paddings)
    if len(p) == 2:
        pt, pb, pl, pr = p[0], p[0], p[1], p[1]
    else:
        pt, pb, pl, pr = p
    def fn(v):
        n, ckk, L = v.shape
        c = ckk // (kh * kw)
        hh, ww = oh + pt + pb, ow + pl + pr
        nh = (hh - (dh * (kh - 1) + 1)) // sh + 1
        nw = (ww - (dw * (kw - 1) + 1)) // sw + 1
        v = v.reshape(n, c, kh, kw, nh, nw)
        out = jnp.zeros((n, c, hh, ww), v.dtype)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wj = j * dw
                out = out.at[:, :, hi:hi + nh * sh:sh, wj:wj + nw * sw:sw].add(
                    v[:, :, i, j])
        return out[:, :, pt:pt + oh, pl:pl + ow]
    return apply_op(fn, (x,))


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample ``num_samples`` class centers always containing the positives.

    For margin-softmax / partial-FC large-class training: the classification
    layer only materializes the sampled columns. Returns
    ``(remapped_label, sampled_class_center)``:
    - sampled_class_center: [num_samples] sorted ascending class ids — every
      class present in ``label`` (while they fit), topped up with uniformly
      random negatives;
    - remapped_label: [N] index of each label within sampled_class_center.

    TPU-first fixed-shape design: one jit-compatible top-k over a random
    priority vector (positives keyed into [0,1), negatives into [1,2)) —
    no host-side set arithmetic, fully static [num_samples] output. If more
    than num_samples distinct positive classes exist, a uniform subset is
    kept and the dropped ones remap to -1.
    """
    label = _t(label)
    if num_samples > num_classes:
        raise ValueError(
            "class_center_sample: num_samples (%d) must be <= num_classes "
            "(%d)" % (num_samples, num_classes))
    if num_samples == num_classes:
        # degenerate: keep every class, identity remap (shape stays
        # [num_samples] as documented)
        def fn_all(lv):
            sampled = jnp.arange(num_classes, dtype=lv.dtype)
            return lv, sampled
        return apply_op(fn_all, (label,), n_outputs=2,
                        differentiable=False)
    key = _rng.next_key()

    def fn(lv):
        lab = lv.reshape(-1).astype(jnp.int32)
        pos = jnp.zeros((num_classes,), jnp.bool_).at[lab].set(True)
        u = jax.random.uniform(key, (num_classes,))
        # positives sort strictly before any negative
        priority = jnp.where(pos, u, u + 1.0)
        _, sampled = jax.lax.top_k(-priority, num_samples)
        sampled = jnp.sort(sampled).astype(lv.dtype)
        table = jnp.full((num_classes,), -1, jnp.int32) \
            .at[sampled].set(jnp.arange(num_samples, dtype=jnp.int32))
        remapped = table[lab].reshape(lv.shape).astype(lv.dtype)
        return remapped, sampled

    return apply_op(fn, (label,), n_outputs=2, differentiable=False)
