"""Convolution functionals. Parity: python/paddle/nn/functional/conv.py.

TPU-first: everything lowers to lax.conv_general_dilated with explicit
dimension numbers; XLA's layout assignment maps it onto the MXU. NCHW (paddle
default) and channel-last formats are both accepted.
"""
import numpy as np
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, apply_op
from ...tensor._helpers import _t

__all__ = ['conv1d', 'conv2d', 'conv3d', 'conv1d_transpose', 'conv2d_transpose',
           'conv3d_transpose']


def _norm_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(i) for i in v)
    if len(v) == 1:
        return v * n
    return v


def _norm_padding(padding, n, strides, dilations, kernel, in_sizes):
    """Returns lax-compatible padding: 'SAME', 'VALID', or explicit pairs."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n:
        if isinstance(padding[0], (list, tuple)):
            return [tuple(p) for p in padding]
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    raise ValueError(f"bad padding {padding}")


def _conv_nd(x, weight, bias, stride, padding, dilation, groups, n,
             channel_last, transpose=False, output_padding=0, output_size=None):
    strides = _norm_tuple(stride, n)
    dilations = _norm_tuple(dilation, n)
    spatial = ''.join('DHW'[3 - n:][i] for i in range(n))
    if channel_last:
        lhs_spec = 'N' + spatial + 'C'
    else:
        lhs_spec = 'NC' + spatial
    # weight layout (paddle): (out, in/groups, *k); transpose: (in, out/groups, *k)
    rhs_spec = ('IO' if transpose else 'OI') + spatial
    out_spec = lhs_spec
    dn = lax.conv_dimension_numbers((1,) * (n + 2), (1,) * (n + 2),
                                    (lhs_spec, rhs_spec, out_spec))
    pad = _norm_padding(padding, n, strides, dilations, None, None)

    def fn(v, w, *maybe_bias):
        from ...amp import maybe_cast_for
        v, w = maybe_cast_for('conv2d', v, w)
        if transpose:
            opad = _norm_tuple(output_padding, n)
            if isinstance(pad, str):
                pads = pad
            else:
                k = [w.shape[2 + i] for i in range(n)]
                pads = [(dilations[i] * (k[i] - 1) - pad[i][0],
                         dilations[i] * (k[i] - 1) - pad[i][1] + opad[i])
                        for i in range(n)]
            out = lax.conv_general_dilated(
                v, jnp.flip(w, axis=tuple(range(2, 2 + n))),
                window_strides=(1,) * n,
                padding=pads if not isinstance(pads, str) else pads,
                lhs_dilation=strides, rhs_dilation=dilations,
                dimension_numbers=dn, feature_group_count=groups)
        else:
            out = lax.conv_general_dilated(
                v, w, window_strides=strides, padding=pad,
                rhs_dilation=dilations, dimension_numbers=dn,
                feature_group_count=groups)
        if maybe_bias:
            b = maybe_bias[0]
            shp = [1] * out.ndim
            c_axis = out.ndim - 1 if channel_last else 1
            shp[c_axis] = b.size
            out = out + b.reshape(shp)
        return out

    args = (x, weight) if bias is None else (x, weight, bias)
    out = apply_op(fn, tuple(_t(a) for a in args))
    if transpose and output_size is not None:
        # crop/verify to requested output size
        pass
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format='NCL', name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    channel_last=(data_format in ('NLC',)))


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    channel_last=(data_format == "NHWC"))


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    channel_last=(data_format == "NDHWC"))


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format='NCL',
                     name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 1,
                    channel_last=(data_format == 'NLC'), transpose=True,
                    output_padding=output_padding, output_size=output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format='NCHW',
                     name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 2,
                    channel_last=(data_format == 'NHWC'), transpose=True,
                    output_padding=output_padding, output_size=output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format='NCDHW',
                     name=None):
    return _conv_nd(x, weight, bias, stride, padding, dilation, groups, 3,
                    channel_last=(data_format == 'NDHWC'), transpose=True,
                    output_padding=output_padding, output_size=output_size)
