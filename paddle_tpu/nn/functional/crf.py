"""Linear-chain CRF: sequence log-likelihood + Viterbi decoding.

Parity: the reference's linear_chain_crf / crf_decoding ops
(paddle/fluid/operators/linear_chain_crf_op.cc, crf_decoding_op.cc;
python surface fluid/layers/nn.py). The reference consumes LoD sequences
and hand-codes the forward/backward recursions in C++; here sequences are
padded-dense [B, T, D] with lengths, the forward algorithm is a log-space
``lax.scan`` (one fused XLA loop, autodiff provides the gradient the
reference's grad op hand-derives), and Viterbi is a scan + reverse-scan
backtrack.

Transition parameter layout (same as the reference):
[(D+2), D] — row 0: start weights, row 1: stop weights, rows 2..D+1:
transition weights w[i, j] for tag i -> tag j.
"""
import jax
import jax.numpy as jnp
from jax import lax

from ...core.tensor import apply_op
from ...tensor._helpers import _t

__all__ = ['linear_chain_crf', 'crf_decoding']


def _split_transition(transition):
    return transition[0], transition[1], transition[2:]


def _seq_nll(emission, label, length, transition):
    """Negative log-likelihood of one padded sequence [T, D], [T]."""
    # jnp-coerce: a Parameter constructed from numpy carries a numpy
    # payload, and numpy advanced indexing rejects traced index arrays
    start, stop, w = _split_transition(jnp.asarray(transition))
    T, D = emission.shape
    t_idx = jnp.arange(T)
    mask = (t_idx < length)
    maskf = mask.astype(emission.dtype)

    # log partition: alpha recursion
    alpha0 = start + emission[0]

    def fwd(alpha, t):
        nxt = jax.nn.logsumexp(alpha[:, None] + w, axis=0) + emission[t]
        alpha = jnp.where(mask[t], nxt, alpha)
        return alpha, None

    alpha, _ = lax.scan(fwd, alpha0, jnp.arange(1, T))
    log_z = jax.nn.logsumexp(alpha + stop)

    # gold path score
    lab = label.astype(jnp.int32)
    emit_score = jnp.sum(
        jnp.take_along_axis(emission, lab[:, None], axis=1)[:, 0] * maskf)
    trans_score = jnp.sum(w[lab[:-1], lab[1:]] * maskf[1:])
    last = lab[jnp.maximum(length - 1, 0)]
    gold = start[lab[0]] + emit_score + trans_score + stop[last]
    return log_z - gold


def linear_chain_crf(emission, label, transition, length=None, name=None):
    """Per-sequence CRF negative log-likelihood (the training cost).

    emission: [B, T, D] unnormalized tag scores; label: [B, T] int tags;
    transition: [(D+2), D] (see module docstring); length: [B] valid
    lengths (defaults to full T). Returns [B, 1] float — ``mean()`` it for
    the loss, exactly how the reference's crf_cost is consumed.
    Differentiable w.r.t. emission and transition.
    """
    emission, label, transition = _t(emission), _t(label), _t(transition)
    if length is None:
        length = jnp.full((emission.shape[0],), emission.shape[1], jnp.int32)
    length = _t(length)

    def fn(e, l, lens, w):
        return jax.vmap(_seq_nll, in_axes=(0, 0, 0, None))(
            e, l, lens.astype(jnp.int32), w)[:, None]
    return apply_op(fn, (emission, label, length, transition))


def _seq_viterbi(emission, length, transition):
    """Best tag path of one padded sequence; padded positions -> 0."""
    start, stop, w = _split_transition(jnp.asarray(transition))
    T, D = emission.shape
    mask = jnp.arange(T) < length

    delta0 = start + emission[0]

    def fwd(delta, t):
        scores = delta[:, None] + w                 # [from, to]
        ptr = jnp.argmax(scores, axis=0)            # best predecessor
        nxt = jnp.max(scores, axis=0) + emission[t]
        keep = mask[t]
        delta = jnp.where(keep, nxt, delta)
        # padded steps point to themselves so backtrack passes through
        ptr = jnp.where(keep, ptr, jnp.arange(D))
        return delta, ptr

    delta, ptrs = lax.scan(fwd, delta0, jnp.arange(1, T))  # ptrs: [T-1, D]
    best_last = jnp.argmax(delta + stop)

    def back(tag, ptr):
        return ptr[tag], tag

    # reverse scan: ys[k] = tag at step k+1, final carry = tag at step 0
    tag0, tail = lax.scan(back, best_last, ptrs, reverse=True)
    path = jnp.concatenate([jnp.array([tag0]), tail])
    return jnp.where(mask, path, 0).astype(jnp.int64)


def crf_decoding(emission, transition, length=None, label=None, name=None):
    """Viterbi-decode the best tag sequence under a linear-chain CRF.

    emission: [B, T, D]; transition: [(D+2), D]; length: [B] (defaults to
    full T). Returns the [B, T] best path (padded positions 0) — or, when
    ``label`` is given, the reference's error mask: 1 at valid positions
    where the decoded tag differs from the label.
    """
    emission, transition = _t(emission), _t(transition)
    tensors = [emission, transition]
    if length is not None:
        tensors.append(_t(length))
    if label is not None:
        tensors.append(_t(label))

    def fn(e, w, *rest):
        rest = list(rest)
        lens = rest.pop(0).astype(jnp.int32) if length is not None \
            else jnp.full((e.shape[0],), e.shape[1], jnp.int32)
        path = jax.vmap(_seq_viterbi, in_axes=(0, 0, None))(e, lens, w)
        if label is None:
            return path
        lab = rest.pop(0).astype(jnp.int64)
        valid = (jnp.arange(e.shape[1])[None, :] < lens[:, None])
        return ((path != lab) & valid).astype(jnp.int64)

    return apply_op(fn, tuple(tensors), differentiable=False)
