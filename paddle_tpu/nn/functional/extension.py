"""Extension ops: sequence ops as masked-dense, diag_embed, temporal_shift.

Parity: python/paddle/nn/functional/extension.py + fluid/layers/sequence_lod.py.
TPU-first divergence: LoD ragged sequences are represented as dense padded
(batch, max_len, ...) tensors + integer lengths / boolean masks (static shapes
for XLA). Each sequence_* op takes `length` or a mask instead of LoD levels.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...tensor._helpers import _t

__all__ = ['diag_embed', 'sequence_mask', 'temporal_shift', 'sequence_pool',
           'sequence_softmax', 'sequence_pad', 'sequence_unpad', 'sequence_expand',
           'sequence_reverse', 'sequence_concat', 'gather_tree']


def diag_embed(input, offset=0, dim1=-2, dim2=-1):
    x = _t(input)
    def fn(v):
        n = v.shape[-1]
        out = jnp.zeros(v.shape + (n + abs(offset),), v.dtype) if offset else None
        m = jnp.zeros(v.shape[:-1] + (n + abs(offset), n + abs(offset)), v.dtype)
        idx = jnp.arange(n)
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        m = m.at[..., r, c].set(v)
        if (dim1, dim2) != (-2, -1):
            m = jnp.moveaxis(m, (-2, -1), (dim1, dim2))
        return m
    return apply_op(fn, (x,))


def sequence_mask(x, maxlen=None, dtype='int64', name=None):
    """Lengths -> binary mask [..., maxlen].

    TPU-first: the mask width is a compile-time constant (XLA has no
    data-dependent shapes), so under jit/to_static ``maxlen`` must be given
    explicitly; eager mode may infer it from ``x.max()`` (host sync).
    """
    x = _t(x)
    if maxlen is None:
        import jax.core as _jcore
        if isinstance(x._value, _jcore.Tracer):
            raise ValueError(
                "sequence_mask(maxlen=None) cannot infer the mask width "
                "from a traced tensor: XLA requires static output shapes. "
                "Pass maxlen explicitly (e.g. the padded sequence length).")
        maxlen = int(np.asarray(x.numpy()).max())
    elif isinstance(maxlen, Tensor):
        maxlen = int(maxlen.item())
    from ...core.dtypes import convert_dtype
    dt = convert_dtype(dtype)
    def fn(v):
        return (jnp.arange(maxlen) < v[..., None]).astype(dt)
    return apply_op(fn, (x,), differentiable=False)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    x = _t(x)
    def fn(v):
        if data_format == "NHWC":
            v = jnp.transpose(v, (0, 3, 1, 2))
        nt, c, h, w = v.shape
        n = nt // seg_num
        v = v.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        pad = jnp.pad(v, [(0, 0), (1, 1), (0, 0), (0, 0), (0, 0)])
        left = pad[:, 2:, :c1]
        mid = pad[:, :-2, c1:c2]
        rest = v[:, :, c2:]
        out = jnp.concatenate([left, mid, rest], axis=2).reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.transpose(out, (0, 2, 3, 1))
        return out
    return apply_op(fn, (x,))


def _length_mask(v, length, dtype):
    return (jnp.arange(v.shape[1]) < length[:, None]).astype(dtype)


def sequence_pool(x, pool_type, length=None, pad_value=0.0):
    """x: (B, T, ...) dense; length: (B,) valid lengths. Parity: sequence_pool."""
    x = _t(x)
    pool_type = pool_type.lower()
    if length is None:
        length = Tensor(jnp.full((x.shape[0],), x.shape[1], dtype=jnp.int32))
    length = _t(length)
    def fn(v, ln):
        mask = _length_mask(v, ln, v.dtype)
        mask = mask.reshape(mask.shape + (1,) * (v.ndim - 2))
        cnt = jnp.maximum(ln.astype(v.dtype), 1.0).reshape(
            (-1,) + (1,) * (v.ndim - 2))
        if pool_type == 'sum':
            return jnp.sum(v * mask, axis=1)
        if pool_type in ('average', 'avg', 'mean'):
            return jnp.sum(v * mask, axis=1) / cnt
        if pool_type == 'sqrt':
            return jnp.sum(v * mask, axis=1) / jnp.sqrt(cnt)
        if pool_type == 'max':
            neg = jnp.asarray(-1e30, v.dtype)
            return jnp.max(jnp.where(mask > 0, v, neg), axis=1)
        if pool_type == 'first':
            return v[:, 0]
        if pool_type == 'last':
            idx = jnp.maximum(ln - 1, 0)
            return jnp.take_along_axis(
                v, idx.reshape((-1, 1) + (1,) * (v.ndim - 2)), axis=1)[:, 0]
        raise ValueError(pool_type)
    return apply_op(fn, (x, length))


def sequence_softmax(x, length=None, axis=1):
    x = _t(x)
    if length is None:
        from .activation import softmax
        return softmax(x, axis=axis)
    length = _t(length)
    def fn(v, ln):
        mask = _length_mask(v, ln, v.dtype)
        logits = jnp.where(mask > 0, v, -1e30)
        return jax.nn.softmax(logits, axis=axis) * mask
    return apply_op(fn, (x, length))


def sequence_pad(x, pad_value, maxlen=None, length=None):
    """Already-dense parity shim: pads time dim to maxlen."""
    x = _t(x)
    if maxlen is None:
        return x, _t(length) if length is not None else None
    def fn(v):
        pad_spec = [(0, 0)] * v.ndim
        pad_spec[1] = (0, maxlen - v.shape[1])
        pv = pad_value.item() if isinstance(pad_value, Tensor) else pad_value
        return jnp.pad(v, pad_spec, constant_values=pv)
    return apply_op(fn, (x,)), (_t(length) if length is not None else None)


def sequence_unpad(x, length):
    """Returns x with positions past `length` zeroed (static-shape analogue)."""
    x, length = _t(x), _t(length)
    def fn(v, ln):
        mask = _length_mask(v, ln, v.dtype)
        return v * mask.reshape(mask.shape + (1,) * (v.ndim - 2))
    return apply_op(fn, (x, length))


def sequence_expand(x, y_lengths, ref_level=0):
    """Repeat each row i of x y_lengths[i] times — static variant: host compute."""
    x = _t(x)
    reps = np.asarray(_t(y_lengths).numpy()).astype(int)
    idx = np.repeat(np.arange(len(reps)), reps)
    return apply_op(lambda v: jnp.take(v, jnp.asarray(idx), axis=0), (x,))


def sequence_reverse(x, length=None):
    x = _t(x)
    if length is None:
        return apply_op(lambda v: jnp.flip(v, axis=1), (x,))
    length = _t(length)
    def fn(v, ln):
        T = v.shape[1]
        pos = jnp.arange(T)
        rev_idx = jnp.where(pos[None, :] < ln[:, None],
                            ln[:, None] - 1 - pos[None, :], pos[None, :])
        return jnp.take_along_axis(
            v, rev_idx.reshape(rev_idx.shape + (1,) * (v.ndim - 2)), axis=1)
    return apply_op(fn, (x, length))


def sequence_concat(inputs, lengths=None):
    """Concat along time with masks (dense shim: plain concat)."""
    ts = tuple(_t(i) for i in inputs)
    return apply_op(lambda *vs: jnp.concatenate(vs, axis=1), ts)


def gather_tree(ids, parents):
    """Beam-search backtrace. ids/parents: (T, B, W)."""
    ids, parents = _t(ids), _t(parents)
    def fn(i, p):
        T = i.shape[0]
        def step(carry, t):
            beams = carry  # (B, W) current beam indices
            out = jnp.take_along_axis(i[t], beams, axis=1)
            new_beams = jnp.take_along_axis(p[t], beams, axis=1)
            return new_beams, out
        init = jnp.broadcast_to(jnp.arange(i.shape[2]), i.shape[1:])
        _, outs = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return jnp.flip(outs, axis=0)
    return apply_op(fn, (ids, parents), differentiable=False)
