"""Input encodings. Parity: python/paddle/nn/functional/input.py."""
from .common import one_hot, embedding  # noqa: F401

__all__ = ['one_hot', 'embedding']
