"""Loss functionals. Parity: python/paddle/nn/functional/loss.py (+ fluid/layers/loss.py)."""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...core.dtypes import is_integer
from ...tensor._helpers import _t

__all__ = ['cross_entropy', 'softmax_with_cross_entropy', 'binary_cross_entropy',
           'binary_cross_entropy_with_logits', 'l1_loss', 'mse_loss',
           'smooth_l1_loss', 'nll_loss', 'kl_div', 'margin_ranking_loss',
           'log_loss', 'sigmoid_focal_loss', 'ctc_loss', 'square_error_cost',
           'hinge_embedding_loss', 'cosine_embedding_loss', 'npair_loss',
           'huber_loss', 'triplet_margin_loss', 'sampled_softmax_with_cross_entropy']


def _reduce_loss(out_fn, reduction):
    def fn(*args):
        out = out_fn(*args)
        if reduction == 'mean':
            return jnp.mean(out)
        if reduction == 'sum':
            return jnp.sum(out)
        return out
    return fn


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction='mean',
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    input, label = _t(input), _t(label)
    tensors = [input, label]
    has_w = weight is not None
    if has_w:
        tensors.append(_t(weight))

    def core(logits, lbl, *w):
        logp = jax.nn.log_softmax(logits, axis=axis) if use_softmax else jnp.log(
            jnp.maximum(logits, 1e-30))
        if soft_label:
            loss = -jnp.sum(lbl * logp, axis=axis)
            valid = jnp.ones_like(loss, dtype=logits.dtype)
        else:
            li = lbl
            if li.ndim == logp.ndim:  # (N, 1) hard labels
                li = jnp.squeeze(li, axis=axis)
            li = li.astype(jnp.int32)
            valid = (li != ignore_index)
            safe = jnp.where(valid, li, 0)
            loss = -jnp.take_along_axis(
                logp, jnp.expand_dims(safe, axis), axis=axis).squeeze(axis)
            if w:
                cw = jnp.take(w[0], safe, axis=0)
                loss = loss * cw
            loss = jnp.where(valid, loss, 0.0)
            valid = valid.astype(logits.dtype)
        if reduction == 'mean':
            denom = jnp.maximum(jnp.sum(valid), 1.0)
            if w and not soft_label:
                li2 = lbl
                if li2.ndim == logp.ndim:
                    li2 = jnp.squeeze(li2, axis=axis)
                safe2 = jnp.where(li2.astype(jnp.int32) != ignore_index,
                                  li2.astype(jnp.int32), 0)
                cw = jnp.take(w[0], safe2, axis=0)
                denom = jnp.maximum(jnp.sum(cw * valid), 1e-12)
            return jnp.sum(loss) / denom
        if reduction == 'sum':
            return jnp.sum(loss)
        return loss
    return apply_op(core, tuple(tensors))


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False,
                               axis=-1):
    logits, label = _t(logits), _t(label)
    def fn(lg, lb):
        sm = jax.nn.softmax(lg, axis=axis)
        logp = jax.nn.log_softmax(lg, axis=axis)
        if soft_label:
            loss = -jnp.sum(lb * logp, axis=axis, keepdims=True)
        else:
            li = lb.astype(jnp.int32)
            squeeze = False
            if li.ndim == lg.ndim:
                li = jnp.squeeze(li, axis)
                squeeze = True
            valid = li != ignore_index
            safe = jnp.where(valid, li, 0)
            loss = -jnp.take_along_axis(logp, jnp.expand_dims(safe, axis), axis=axis)
            loss = jnp.where(jnp.expand_dims(valid, axis), loss, 0.0)
        return (loss, sm)
    loss, sm = apply_op(fn, (logits, label), n_outputs=2)
    if return_softmax:
        return loss, sm
    return loss


def binary_cross_entropy(input, label, weight=None, reduction='mean', name=None):
    tensors = [_t(input), _t(label)]
    if weight is not None:
        tensors.append(_t(weight))
    def core(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        out = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            out = out * w[0]
        return out
    return apply_op(_reduce_loss(core, reduction), tuple(tensors))


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction='mean',
                                     pos_weight=None, name=None):
    tensors = [_t(logit), _t(label)]
    has_w = weight is not None
    has_pw = pos_weight is not None
    if has_w:
        tensors.append(_t(weight))
    if has_pw:
        tensors.append(_t(pos_weight))
    def core(x, y, *rest):
        i = 0
        w = rest[i] if has_w else None
        if has_w:
            i += 1
        pw = rest[i] if has_pw else None
        max_val = jnp.maximum(-x, 0)
        if pw is not None:
            log_weight = (pw - 1) * y + 1
            loss = (1 - y) * x + log_weight * (
                jnp.log(jnp.exp(-max_val) + jnp.exp(-x - max_val)) + max_val)
        else:
            loss = (1 - y) * x + max_val + jnp.log(
                jnp.exp(-max_val) + jnp.exp(-x - max_val))
        if w is not None:
            loss = loss * w
        return loss
    return apply_op(_reduce_loss(core, reduction), tuple(tensors))


def l1_loss(input, label, reduction='mean', name=None):
    return apply_op(_reduce_loss(lambda x, y: jnp.abs(x - y), reduction),
                    (_t(input), _t(label)))


def mse_loss(input, label, reduction='mean', name=None):
    return apply_op(_reduce_loss(lambda x, y: (x - y) ** 2, reduction),
                    (_t(input), _t(label)))


def square_error_cost(input, label):
    return apply_op(lambda x, y: (x - y) ** 2, (_t(input), _t(label)))


def smooth_l1_loss(input, label, reduction='mean', delta=1.0, name=None):
    def core(x, y):
        d = jnp.abs(x - y)
        return jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta) * delta
    return apply_op(_reduce_loss(
        lambda x, y: jnp.where(jnp.abs(x - y) < delta,
                               0.5 * (x - y) ** 2 / delta,
                               jnp.abs(x - y) - 0.5 * delta) * delta, reduction),
        (_t(input), _t(label)))


def huber_loss(input, label, delta=1.0, reduction='mean', name=None):
    return apply_op(_reduce_loss(
        lambda x, y: jnp.where(jnp.abs(x - y) <= delta,
                               0.5 * (x - y) ** 2,
                               delta * (jnp.abs(x - y) - 0.5 * delta)), reduction),
        (_t(input), _t(label)))


def nll_loss(input, label, weight=None, ignore_index=-100, reduction='mean',
             name=None):
    tensors = [_t(input), _t(label)]
    if weight is not None:
        tensors.append(_t(weight))
    def core(logp, y, *w):
        y = y.astype(jnp.int32)
        valid = y != ignore_index
        safe = jnp.where(valid, y, 0)
        if logp.ndim > 2:  # (N, C, d1, ...) -> move C last
            perm = (0,) + tuple(range(2, logp.ndim)) + (1,)
            logp_m = jnp.transpose(logp, perm)
        else:
            logp_m = logp
        loss = -jnp.take_along_axis(logp_m, safe[..., None], axis=-1)[..., 0]
        cw = None
        if w:
            cw = jnp.take(w[0], safe, axis=0)
            loss = loss * cw
        loss = jnp.where(valid, loss, 0.0)
        if reduction == 'mean':
            denom = jnp.sum((cw if cw is not None else 1.0) *
                            valid.astype(loss.dtype))
            return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
        if reduction == 'sum':
            return jnp.sum(loss)
        return loss
    return apply_op(core, tuple(tensors))


def kl_div(input, label, reduction='mean', name=None):
    def core(logp, y):
        return y * (jnp.log(jnp.maximum(y, 1e-30)) - logp)
    if reduction == 'batchmean':
        def fn(logp, y):
            return jnp.sum(core(logp, y)) / logp.shape[0]
        return apply_op(fn, (_t(input), _t(label)))
    return apply_op(_reduce_loss(core, reduction), (_t(input), _t(label)))


def margin_ranking_loss(input, other, label, margin=0.0, reduction='mean',
                        name=None):
    return apply_op(_reduce_loss(
        lambda x, o, y: jnp.maximum(-y * (x - o) + margin, 0.0), reduction),
        (_t(input), _t(other), _t(label)))


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_op(
        lambda p, y: -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon),
        (_t(input), _t(label)))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction='sum', name=None):
    tensors = [_t(logit), _t(label)]
    if normalizer is not None:
        tensors.append(_t(normalizer))
    def core(x, y, *nrm):
        p = jax.nn.sigmoid(x)
        ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if nrm:
            loss = loss / nrm[0]
        if reduction == 'mean':
            return jnp.mean(loss)
        if reduction == 'sum':
            return jnp.sum(loss)
        return loss
    return apply_op(core, tuple(tensors))


def hinge_embedding_loss(input, label, margin=1.0, reduction='mean', name=None):
    return apply_op(_reduce_loss(
        lambda x, y: jnp.where(y == 1., x, jnp.maximum(0., margin - x)), reduction),
        (_t(input), _t(label)))


def cosine_embedding_loss(input1, input2, label, margin=0, reduction='mean',
                          name=None):
    def core(a, b, y):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        return jnp.where(y == 1, 1 - cos, jnp.maximum(0., cos - margin))
    return apply_op(_reduce_loss(core, reduction),
                    (_t(input1), _t(input2), _t(label)))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction='mean', name=None):
    def core(a, pos, neg):
        d_ap = jnp.linalg.norm(a - pos + epsilon, ord=p, axis=-1)
        d_an = jnp.linalg.norm(a - neg + epsilon, ord=p, axis=-1)
        if swap:
            d_pn = jnp.linalg.norm(pos - neg + epsilon, ord=p, axis=-1)
            d_an = jnp.minimum(d_an, d_pn)
        return jnp.maximum(d_ap - d_an + margin, 0.)
    return apply_op(_reduce_loss(core, reduction),
                    (_t(input), _t(positive), _t(negative)))


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, y):
        batch = a.shape[0]
        sim = jnp.matmul(a, p.T)
        y = y.reshape(-1, 1)
        target = (y == y.T).astype(a.dtype)
        target = target / jnp.sum(target, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -jnp.mean(jnp.sum(target * logp, axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1)) +
                        jnp.mean(jnp.sum(p * p, 1))) * 0.25
        return ce + reg
    return apply_op(fn, (_t(anchor), _t(positive), _t(labels)))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction='mean', norm_by_times=False):
    """CTC via dynamic-programming in log space (lax.scan over time).

    log_probs: (T, N, C) logits (softmax applied internally, matching
    paddle's warpctc on raw logits).
    """
    lp, lab = _t(log_probs), _t(labels)
    il, ll = _t(input_lengths), _t(label_lengths)

    def fn(logits, labels_, in_len, lab_len):
        logp = jax.nn.log_softmax(logits, axis=-1)
        T, N, C = logp.shape
        S = labels_.shape[1]
        ext = 2 * S + 1
        neg_inf = jnp.asarray(-1e30, logp.dtype)
        # extended label seq: blank, l1, blank, l2, ... blank
        ext_labels = jnp.full((N, ext), blank, dtype=jnp.int32)
        ext_labels = ext_labels.at[:, 1::2].set(labels_.astype(jnp.int32))
        # alpha init
        alpha0 = jnp.full((N, ext), neg_inf)
        alpha0 = alpha0.at[:, 0].set(logp[0, jnp.arange(N), blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0,
                      logp[0, jnp.arange(N), ext_labels[:, 1]], neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), dtype=bool),
             ext_labels[:, 2:] == ext_labels[:, :-2]], axis=1)

        def step(alpha, t):
            a_prev = alpha
            a_shift1 = jnp.concatenate(
                [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
            emit = jnp.take_along_axis(logp[t], ext_labels, axis=1)
            new_alpha = merged + emit
            new_alpha = jnp.where(t < in_len[:, None], new_alpha, alpha)
            return new_alpha, None

        alpha_T, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        end1 = 2 * lab_len.astype(jnp.int32)
        end2 = 2 * lab_len.astype(jnp.int32) - 1
        idx = jnp.arange(N)
        ll_final = jnp.logaddexp(
            alpha_T[idx, end1],
            jnp.where(end2 >= 0, alpha_T[idx, jnp.maximum(end2, 0)], neg_inf))
        loss = -ll_final
        if reduction == 'mean':
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1.0))
        if reduction == 'sum':
            return jnp.sum(loss)
        return loss
    return apply_op(fn, (lp, lab, il, ll))


def sampled_softmax_with_cross_entropy(logits, label, num_samples, **kwargs):
    """Parity shim: full softmax (TPU MXU makes full-vocab softmax cheap)."""
    return softmax_with_cross_entropy(logits, label)
