"""Normalization functionals. Parity: python/paddle/nn/functional/norm.py.

batch_norm takes/returns running stats explicitly in functional form so the
stateful layer can collect updates (see layer_base.functional_call).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...tensor._helpers import _t

__all__ = ['normalize', 'batch_norm', 'layer_norm', 'fused_dropout_add_layer_norm',
           'instance_norm', 'group_norm',
           'local_response_norm', 'rms_norm']


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        if p == 2:
            nrm = jnp.sqrt(jnp.sum(v * v, axis=axis, keepdims=True))
        else:
            nrm = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(nrm, epsilon)
    return apply_op(fn, (_t(x),))


def _channel_shape(v_ndim, c, data_format):
    shp = [1] * v_ndim
    ch_axis = v_ndim - 1 if not data_format.startswith('NC') else 1
    shp[ch_axis] = c
    return shp, ch_axis


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05, data_format="NCHW",
               use_global_stats=None, name=None):
    """Returns normalized output; updates running stats in-place on the
    provided tensors when training (collected by functional_call)."""
    x = _t(x)
    rm, rv = _t(running_mean), _t(running_var)
    use_batch_stats = training and not use_global_stats

    tensors = [x]
    has_affine = weight is not None
    if has_affine:
        tensors += [_t(weight), _t(bias)]

    c = rm.shape[0]
    shp, ch_axis = _channel_shape(x.ndim, c, data_format)
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)

    if use_batch_stats:
        n = int(np.prod([x.shape[i] for i in reduce_axes]))
        unbias = n / max(n - 1, 1)
        # running stats are apply_op INPUTS and the new stats are computed
        # inside the pure fn — this keeps the whole update visible to traces
        # (jit.to_static capture watch) so no tracer ever leaks into buffers.
        tensors += [rm, rv]

        def fn(v, *rest):
            wb, (m0, v0) = rest[:-2], rest[-2:]
            # shifted single-pass stats in fp32: one fused sweep computes
            # E[x-s] and E[(x-s)^2] with s = running mean, so the
            # var = E[(x-s)^2] - E[x-s]^2 subtraction cancels only when
            # |batch mean - s| >> std — which the running mean prevents —
            # instead of whenever |mean| >> std (the naive E[x^2]-E[x]^2).
            vf = v.astype(jnp.float32)
            s = jax.lax.stop_gradient(m0.astype(jnp.float32)).reshape(shp)
            vc = vf - s
            mean_c = jnp.mean(vc, axis=reduce_axes)
            m2 = jnp.mean(vc * vc, axis=reduce_axes)
            var = jnp.maximum(m2 - mean_c * mean_c, 0.0)
            mean = mean_c + s.reshape(mean_c.shape)
            inv = jax.lax.rsqrt(var.reshape(shp) + epsilon)
            out = ((vf - mean.reshape(shp)) * inv).astype(v.dtype)
            if wb:
                out = out * wb[0].reshape(shp) + wb[1].reshape(shp)
            new_rm = momentum * m0 + (1 - momentum) * mean.astype(m0.dtype)
            new_rv = momentum * v0 + (1 - momentum) * (var * unbias).astype(v0.dtype)
            return out, new_rm, new_rv

        def eval_fn(v, *rest):
            # test-mode variant (Program.clone(for_test=True)): normalize
            # with the running stats, leave them unchanged
            wb, (m0, v0) = rest[:-2], rest[-2:]
            inv = 1.0 / jnp.sqrt(v0.astype(jnp.float32).reshape(shp) +
                                 epsilon)
            out = ((v.astype(jnp.float32) -
                    m0.astype(jnp.float32).reshape(shp)) * inv) \
                .astype(v.dtype)
            if wb:
                out = out * wb[0].reshape(shp) + wb[1].reshape(shp)
            return out, m0, v0

        out, new_rm, new_rv = apply_op(fn, tuple(tensors), n_outputs=3,
                                       eval_fn=eval_fn)
        if not getattr(new_rm, '_symbolic', False):
            with _no_grad():
                rm._inplace_value(new_rm._value)
                rv._inplace_value(new_rv._value)
        # static capture: the buffers keep their concrete payloads (writing
        # a symbolic aval into them would poison every later read);
        # running-stat advancement across Executor.run calls is a
        # documented divergence of the static path
        return out

    tensors += [rm, rv]
    def fn(v, *rest):
        if has_affine:
            w, b, m, var = rest
        else:
            (m, var) = rest
            w = b = None
        inv = 1.0 / jnp.sqrt(var.reshape(shp) + epsilon)
        out = (v - m.reshape(shp)) * inv
        if w is not None:
            out = out * w.reshape(shp) + b.reshape(shp)
        return out
    return apply_op(fn, tuple(tensors))


def _no_grad():
    from ...core.autograd import no_grad
    return no_grad()


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    x = _t(x)
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_norm = len(list(normalized_shape))
    axes = tuple(range(x.ndim - n_norm, x.ndim))
    tensors = [x]
    if weight is not None:
        tensors.append(_t(weight))
    if bias is not None:
        tensors.append(_t(bias))
    has_w = weight is not None
    has_b = bias is not None

    if (n_norm == 1 and jax.default_backend() == 'tpu'
            and x.shape[-1] % 128 == 0):
        from ...kernels.fused_norm import fused_layer_norm

        def fused(v, *wb):
            i = 0
            w = wb[i] if has_w else None
            i += has_w
            b = wb[i] if has_b else None
            return fused_layer_norm(v, w, b, eps=epsilon)
        return apply_op(fused, tuple(tensors))

    def fn(v, *wb):
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) / jnp.sqrt(var + epsilon)
        i = 0
        if has_w:
            out = out * wb[i]
            i += 1
        if has_b:
            out = out + wb[i]
        return out
    return apply_op(fn, tuple(tensors))


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (modern LLM stacks; pallas-fused variant in kernels/)."""
    x = _t(x)
    tensors = [x] + ([_t(weight)] if weight is not None else [])
    if jax.default_backend() == 'tpu' and x.shape[-1] % 128 == 0:
        from ...kernels.fused_norm import fused_rms_norm

        def fused(v, *w):
            return fused_rms_norm(v, w[0] if w else None, eps=epsilon)
        return apply_op(fused, tuple(tensors))

    def fn(v, *w):
        ms = jnp.mean(v * v, axis=-1, keepdims=True)
        out = v / jnp.sqrt(ms + epsilon)
        if w:
            out = out * w[0]
        return out
    return apply_op(fn, tuple(tensors))


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    x = _t(x)
    ch_axis = 1 if data_format.startswith('NC') else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i not in (0, ch_axis))
    tensors = [x]
    has_affine = weight is not None
    if has_affine:
        tensors += [_t(weight), _t(bias)]
    def fn(v, *wb):
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) / jnp.sqrt(var + eps)
        if wb:
            shp = [1] * v.ndim
            shp[ch_axis] = wb[0].size
            out = out * wb[0].reshape(shp) + wb[1].reshape(shp)
        return out
    return apply_op(fn, tuple(tensors))


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None,
               data_format="NCHW", name=None):
    x = _t(x)
    ch_axis = 1 if data_format.startswith('NC') else x.ndim - 1
    tensors = [x]
    has_affine = weight is not None
    if has_affine:
        tensors += [_t(weight), _t(bias)]
    def fn(v, *wb):
        if ch_axis != 1:
            v = jnp.moveaxis(v, ch_axis, 1)
        n, c = v.shape[0], v.shape[1]
        rest = v.shape[2:]
        g = v.reshape(n, num_groups, c // num_groups, *rest)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) / jnp.sqrt(var + epsilon)).reshape(v.shape)
        if wb:
            shp = [1] * v.ndim
            shp[1] = c
            out = out * wb[0].reshape(shp) + wb[1].reshape(shp)
        if ch_axis != 1:
            out = jnp.moveaxis(out, 1, ch_axis)
        return out
    return apply_op(fn, tuple(tensors))


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    x = _t(x)
    ch_axis = 1 if data_format.startswith('NC') else x.ndim - 1
    def fn(v):
        sq = v * v
        half = size // 2
        pad_spec = [(0, 0)] * v.ndim
        pad_spec[ch_axis] = (half, size - 1 - half)
        padded = jnp.pad(sq, pad_spec)
        # sliding sum over channel axis
        acc = jnp.zeros_like(v)
        for i in range(size):
            sl = [slice(None)] * v.ndim
            sl[ch_axis] = slice(i, i + v.shape[ch_axis])
            acc = acc + padded[tuple(sl)]
        div = (k + alpha * acc) ** beta
        return v / div
    return apply_op(fn, (x,))


_USE_FUSED_DROPOUT_NORM = [True]
_FUSED_DROPOUT_NORM_MIN_ROWS = 4096  # measured on v5e: below this the pallas
# pass (extra pre-norm-sum write) loses to XLA's own dropout+add fusion


def set_fused_dropout_norm(enabled):
    _USE_FUSED_DROPOUT_NORM[0] = bool(enabled)


def fused_dropout_add_layer_norm(x, residual, weight=None, bias=None,
                                 dropout_p=0.0, epsilon=1e-5, training=True,
                                 name=None):
    """y = LayerNorm(residual + dropout(x)) — single pallas pass on TPU.

    Replaces the three separate HBM passes (rng mask, dropout select,
    residual add) + norm read of the unfused transformer sublayer epilogue
    (kernels/fused_dropout_norm.py). Off-TPU falls back to composed ops with
    identical semantics.
    """
    from ...core import rng as _rng
    x, residual = _t(x), _t(residual)
    p_eff = float(dropout_p) if training else 0.0
    tensors = [x, residual]
    has_w = weight is not None
    has_b = bias is not None
    if has_w:
        tensors.append(_t(weight))
    if has_b:
        tensors.append(_t(bias))
    n_rows = 1
    for s in x.shape[:-1]:
        n_rows *= s
    if (_USE_FUSED_DROPOUT_NORM[0] and n_rows >= _FUSED_DROPOUT_NORM_MIN_ROWS
            and jax.default_backend() == 'tpu' and x.shape[-1] % 128 == 0):
        from ...kernels.fused_dropout_norm import \
            fused_dropout_add_layer_norm as _kernel
        seed = None
        if p_eff > 0.0:
            seed = jax.random.randint(_rng.next_key(), (1, 1), 0,
                                      2**31 - 1).astype(jnp.int32)

        def fused(v, r, *wb):
            i = 0
            w = wb[i] if has_w else None
            i += has_w
            b = wb[i] if has_b else None
            return _kernel(v, r, w, b, dropout_p=p_eff, epsilon=epsilon,
                           dropout_seed=seed)
        return apply_op(fused, tuple(tensors))

    # composed fallback (identical math, separate passes)
    from .common import dropout as _dropout
    y = _dropout(x, p=p_eff, training=True) if p_eff > 0.0 else x
    s = apply_op(lambda a, b: a + b, (y, residual))
    return layer_norm(s, x.shape[-1], weight, bias, epsilon)
