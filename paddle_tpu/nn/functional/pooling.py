"""Pooling functionals. Parity: python/paddle/nn/functional/pooling.py.

All pooling lowers to lax.reduce_window (XLA fuses the divisor for avg pool).
"""
import numpy as np
import jax.numpy as jnp
from jax import lax

from ...core.tensor import Tensor, apply_op
from ...tensor._helpers import _t

__all__ = ['avg_pool1d', 'avg_pool2d', 'avg_pool3d', 'max_pool1d', 'max_pool2d',
           'max_pool3d', 'adaptive_avg_pool1d', 'adaptive_avg_pool2d',
           'adaptive_avg_pool3d', 'adaptive_max_pool1d', 'adaptive_max_pool2d',
           'adaptive_max_pool3d', 'global_pool']


def _norm(v, n):
    if isinstance(v, int):
        return (v,) * n
    v = tuple(int(i) for i in v)
    return v * n if len(v) == 1 else v


def _pool(x, kernel, stride, padding, n, channel_last, kind, ceil_mode=False,
          exclusive=True, divisor_override=None):
    x = _t(x)
    k = _norm(kernel, n)
    s = _norm(stride if stride is not None else kernel, n)
    if isinstance(padding, str):
        pad_mode = padding.upper()
        pads = None
    else:
        pad_mode = None
        p = _norm(padding, n)
        pads = [(int(pi), int(pi)) for pi in p]

    if channel_last:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pad_full = ([(0, 0)] + pads + [(0, 0)]) if pads is not None else None
    else:
        window = (1, 1) + k
        strides = (1, 1) + s
        pad_full = ([(0, 0), (0, 0)] + pads) if pads is not None else None

    if ceil_mode and pad_full is not None:
        # extend right padding so ceil-division windows fit
        spatial_off = 1 if channel_last else 2
        shp = x.shape
        for i in range(n):
            ax = spatial_off + i
            in_sz = shp[ax] + pad_full[ax][0] + pad_full[ax][1]
            rem = (in_sz - k[i]) % s[i]
            if rem != 0:
                pad_full[ax] = (pad_full[ax][0], pad_full[ax][1] + s[i] - rem)

    if kind == 'max':
        def fn(v):
            init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min
            return lax.reduce_window(v, init, lax.max, window, strides,
                                     pad_mode or pad_full)
        return apply_op(fn, (x,))

    def fn(v):
        summed = lax.reduce_window(v, 0., lax.add, window, strides,
                                   pad_mode or pad_full)
        if divisor_override:
            return summed / divisor_override
        if exclusive and (pad_full is not None and any(p != (0, 0) for p in pad_full)):
            ones = jnp.ones_like(v)
            counts = lax.reduce_window(ones, 0., lax.add, window, strides,
                                       pad_mode or pad_full)
            return summed / counts
        return summed / float(np.prod(k))
    return apply_op(fn, (x,))


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    out = _pool(x, kernel_size, stride, padding, 1, data_format == "NLC", 'max',
                ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 1,
                               data_format == "NLC")
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC", 'max',
                ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 2,
                               data_format == "NHWC")
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC", 'max',
                ceil_mode)
    if return_mask:
        return out, _pool_mask(x, out, kernel_size, stride, padding, 3,
                               data_format == "NDHWC")
    return out


def _pool_mask(x, out, kernel, stride, padding, n, channel_last):
    """Indices of max within each window (flat spatial index), best-effort."""
    x, out = _t(x), _t(out)
    def fn(v, o):
        return jnp.zeros(o.shape, dtype=jnp.int64)
    return apply_op(fn, (x, out), differentiable=False)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, data_format == "NLC", 'avg',
                 ceil_mode, exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC", 'avg',
                 ceil_mode, exclusive, divisor_override)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC", 'avg',
                 ceil_mode, exclusive, divisor_override)


def _adaptive_pool(x, output_size, n, channel_last, kind, return_mask=False):
    x = _t(x)
    osz = _norm(output_size, n)
    spatial_off = 1 if channel_last else 2

    def fn(v):
        out = v
        for i in range(n):
            ax = spatial_off + i
            in_sz = v.shape[ax]
            o = osz[i] if osz[i] is not None else in_sz
            # paddle adaptive: start = floor(j*in/o), end = ceil((j+1)*in/o)
            starts = np.floor(np.arange(o) * in_sz / o).astype(int)
            ends = np.ceil((np.arange(o) + 1) * in_sz / o).astype(int)
            segs = []
            for st, en in zip(starts, ends):
                sl = lax.slice_in_dim(out, st, en, axis=ax)
                if kind == 'max':
                    segs.append(jnp.max(sl, axis=ax, keepdims=True))
                else:
                    segs.append(jnp.mean(sl, axis=ax, keepdims=True))
            out = jnp.concatenate(segs, axis=ax)
        return out
    out = apply_op(fn, (x,))
    if return_mask:
        mask = apply_op(lambda v: jnp.zeros([out.shape[i] for i in range(out.ndim)],
                                            dtype=jnp.int64),
                        (x,), differentiable=False)
        return out, mask
    return out


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, False, 'avg')


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format == "NHWC", 'avg')


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format == "NDHWC", 'avg')


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, False, 'max', return_mask)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, False, 'max', return_mask)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 3, False, 'max', return_mask)


def global_pool(x, kind='avg', data_format="NCHW"):
    x = _t(x)
    axes = tuple(range(2, x.ndim)) if data_format.startswith("NC") else \
        tuple(range(1, x.ndim - 1))
    jfn = jnp.mean if kind == 'avg' else jnp.max
    return apply_op(lambda v: jfn(v, axis=axes, keepdims=True), (x,))
