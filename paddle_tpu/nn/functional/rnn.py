"""RNN scan helper: run a cell over time with lax.scan (TPU-friendly static loop).

Parity: the C++ RNN compute in paddle/fluid/operators/rnn_op.* — redesigned as
a functional scan over a pure cell function.
"""
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...tensor._helpers import _t

__all__ = ['rnn_scan']


def rnn_scan(cell_fn, x, init_state, time_major=False, reverse=False,
             sequence_length=None, extra_params=()):
    """cell_fn(carry_state, x_t, *params) -> (new_state, out_t) on raw arrays.

    x: Tensor (B, T, I) or (T, B, I) if time_major. init_state: pytree of
    Tensors. Returns (outputs Tensor, final_state pytree of Tensors).
    """
    x = _t(x)
    flat_state, treedef = jax.tree_util.tree_flatten(init_state)
    flat_state = [_t(s) for s in flat_state]
    params = tuple(_t(p) for p in extra_params)
    tensors = (x, *flat_state, *params)
    n_state = len(flat_state)
    has_len = sequence_length is not None
    if has_len:
        tensors = tensors + (_t(sequence_length),)

    def fn(xv, *rest):
        if has_len:
            seq_len = rest[-1]
            rest = rest[:-1]
        states = rest[:n_state]
        ps = rest[n_state:]
        xs = xv if time_major else jnp.swapaxes(xv, 0, 1)  # (T, B, I)
        if reverse:
            xs = jnp.flip(xs, axis=0)
        T = xs.shape[0]
        state0 = jax.tree_util.tree_unflatten(treedef, list(states))

        def step(carry, inp):
            t, st = carry
            new_st, out = cell_fn(st, inp, *ps)
            if has_len:
                # freeze state past each row's length
                def sel(new, old):
                    mask = (t < seq_len).reshape((-1,) + (1,) * (new.ndim - 1))
                    return jnp.where(mask, new, old)
                new_st = jax.tree_util.tree_map(sel, new_st, st)
                mask = (t < seq_len).reshape((-1,) + (1,) * (out.ndim - 1))
                out = jnp.where(mask, out, jnp.zeros_like(out))
            return (t + 1, new_st), out

        if reverse and has_len:
            # reversed pass with lengths: flip valid prefix per row
            idx = jnp.arange(T)
            rev_idx = jnp.where(idx[None, :] < seq_len[:, None],
                                seq_len[:, None] - 1 - idx[None, :], idx[None, :])
            xs_bt = jnp.swapaxes(xs, 0, 1)
            xs_bt = jnp.take_along_axis(
                xs_bt, rev_idx.reshape(rev_idx.shape + (1,) * (xs_bt.ndim - 2)),
                axis=1)
            xs = jnp.swapaxes(xs_bt, 0, 1)

        (_, final), outs = jax.lax.scan(step, (0, state0), xs)
        if reverse:
            if has_len:
                outs_bt = jnp.swapaxes(outs, 0, 1)
                idx = jnp.arange(T)
                rev_idx = jnp.where(idx[None, :] < seq_len[:, None],
                                    seq_len[:, None] - 1 - idx[None, :],
                                    idx[None, :])
                outs_bt = jnp.take_along_axis(
                    outs_bt,
                    rev_idx.reshape(rev_idx.shape + (1,) * (outs_bt.ndim - 2)),
                    axis=1)
                outs = jnp.swapaxes(outs_bt, 0, 1)
            else:
                outs = jnp.flip(outs, axis=0)
        if not time_major:
            outs = jnp.swapaxes(outs, 0, 1)
        flat_final, _ = jax.tree_util.tree_flatten(final)
        return (outs, *flat_final)

    outs = apply_op(fn, tensors, n_outputs=1 + n_state)
    out_seq = outs[0]
    final_state = jax.tree_util.tree_unflatten(treedef, list(outs[1:]))
    return out_seq, final_state
