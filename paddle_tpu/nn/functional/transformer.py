"""Attention functionals.

Parity: python/paddle/nn/layer/transformer.py core compute. TPU-first: one
fused softmax(QK^T/sqrt(d))V expression XLA can fuse; the pallas flash
attention kernel in kernels/flash_attention.py is used automatically for long
sequences on TPU.
"""
import math

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...tensor._helpers import _t

__all__ = ['scaled_dot_product_attention', 'multi_head_attention']

_USE_FLASH = [True]
_FLASH_MIN_SEQ = 1024  # below this, plain XLA fusion wins


def set_flash_attention(enabled):
    _USE_FLASH[0] = bool(enabled)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """query/key/value: (B, L, H, D) paddle-style. Returns (B, L, H, D)."""
    q, k, v = _t(query), _t(key), _t(value)
    tensors = [q, k, v]
    if attn_mask is not None:
        tensors.append(_t(attn_mask))

    seq_len = q.shape[1]
    use_flash = (_USE_FLASH[0] and is_causal and attn_mask is None and
                 dropout_p == 0.0 and seq_len >= _FLASH_MIN_SEQ and
                 jax.default_backend() == 'tpu')
    if use_flash:
        from ...kernels.flash_attention import flash_attention_bhld
        def ffn(qq, kk, vv):
            # (B, L, H, D) -> (B, H, L, D)
            qq, kk, vv = (jnp.swapaxes(t, 1, 2) for t in (qq, kk, vv))
            out = flash_attention_bhld(qq, kk, vv, causal=True)
            return jnp.swapaxes(out, 1, 2)
        return apply_op(ffn, (q, k, v))

    def fn(qq, kk, vv, *mask):
        d = qq.shape[-1]
        scale = 1.0 / math.sqrt(d)
        # (B, L, H, D) -> (B, H, L, D)
        qq = jnp.swapaxes(qq, 1, 2)
        kk = jnp.swapaxes(kk, 1, 2)
        vv = jnp.swapaxes(vv, 1, 2)
        scores = jnp.einsum('bhld,bhmd->bhlm', qq, kk) * scale
        if mask:
            m = mask[0]
            if m.dtype == jnp.bool_:
                scores = jnp.where(m, scores, -1e30)
            else:
                scores = scores + m
        if is_causal:
            L, M = scores.shape[-2], scores.shape[-1]
            causal = jnp.tril(jnp.ones((L, M), dtype=bool))
            scores = jnp.where(causal, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum('bhlm,bhmd->bhld', probs, vv)
        return jnp.swapaxes(out, 1, 2)
    return apply_op(fn, tuple(tensors))


def multi_head_attention(query, key, value, num_heads, wq, wk, wv, wo,
                         bq=None, bk=None, bv=None, bo=None, attn_mask=None,
                         dropout_p=0.0, is_causal=False, cache=None,
                         training=True):
    """Functional MHA on (B, L, E) with (E, E) projection weights."""
    from .common import linear, dropout as _dropout
    q = linear(query, wq, bq)
    k = linear(key, wk, bk)
    v = linear(value, wv, bv)
    B, Lq, E = q.shape
    hd = E // num_heads
    q = q.reshape([B, Lq, num_heads, hd])
    k = k.reshape([B, k.shape[1], num_heads, hd])
    v = v.reshape([B, v.shape[1], num_heads, hd])
    if cache is not None:
        k = cache.append_k(k)
        v = cache.append_v(v)
    out = scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                       dropout_p=dropout_p, is_causal=is_causal,
                                       training=training)
    out = out.reshape([B, Lq, E])
    return linear(out, wo, bo)
