"""Attention functionals.

Parity: python/paddle/nn/layer/transformer.py core compute. TPU-first: one
fused softmax(QK^T/sqrt(d))V expression XLA can fuse; the pallas flash
attention kernel in kernels/flash_attention.py is used automatically for long
sequences on TPU.
"""
import math

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...tensor._helpers import _t

__all__ = ['scaled_dot_product_attention', 'multi_head_attention']

_USE_FLASH = [True]
_FLASH_MIN_SEQ = 512  # below this, plain XLA fusion wins (measured on-chip)


def set_flash_attention(enabled):
    _USE_FLASH[0] = bool(enabled)


def _mask_as_kpad_bias(m, batch, lk):
    """Convert a (B|1, 1, 1, Lk) boolean/additive mask — the shape BERT-style
    key-padding masks take — to the (B, Lk) additive bias the flash kernel
    streams. Returns None for any other mask shape (caller falls back to the
    dense path)."""
    if m.ndim != 4 or m.shape[1] != 1 or m.shape[2] != 1:
        return None
    if m.shape[3] != lk or m.shape[0] not in (1, batch):
        return None
    bias = m.reshape((m.shape[0], lk))
    if bias.dtype == jnp.bool_:
        bias = jnp.where(bias, 0.0, -1e9).astype(jnp.float32)
    if bias.shape[0] == 1:
        bias = jnp.broadcast_to(bias, (batch, lk))
    return bias


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """query/key/value: (B, L, H, D) paddle-style. Returns (B, L, H, D)."""
    q, k, v = _t(query), _t(key), _t(value)
    tensors = [q, k, v]
    if attn_mask is not None:
        tensors.append(_t(attn_mask))

    seq_len = q.shape[1]
    p_eff = float(dropout_p) if training else 0.0
    am = _t(attn_mask) if attn_mask is not None else None
    mask_flashable = (am is None or
                      (am.ndim == 4 and am.shape[1] == 1 and
                       am.shape[2] == 1 and am.shape[3] == k.shape[1] and
                       am.shape[0] in (1, q.shape[0])))
    flash_eligible = (_USE_FLASH[0] and mask_flashable and
                      seq_len == k.shape[1] and
                      jax.default_backend() == 'tpu')
    # on-chip autotuned decision (kernels/autotune.py) overrides the static
    # threshold when this shape signature has been measured; shapes are
    # concrete even under tracing, so the lookup is trace-safe
    tuned = None
    if flash_eligible:
        from ...kernels.autotune import lookup as _at_lookup
        n_heads = q.shape[2] if q.ndim == 4 else 1
        tuned = _at_lookup(q.shape[0], n_heads, seq_len, q.shape[-1],
                           is_causal, am is not None, p_eff,
                           dtype=str(q.dtype))
    if tuned is not None:
        use_flash = tuned['mode'] == 'flash'
    else:
        use_flash = flash_eligible and seq_len >= _FLASH_MIN_SEQ
    if use_flash:
        from ...kernels.flash_attention import flash_attention_bhld
        blocks = ({'block_q': tuned['block_q'],
                   'block_k': tuned['block_k']} if tuned else {})
        seed = None
        if p_eff > 0.0:
            from ...core import rng as _rng
            seed = jax.random.randint(_rng.next_key(), (1, 1), 0, 2**31 - 1
                                      ).astype(jnp.int32)

        def ffn(qq, kk, vv, *mask):
            kpad = (_mask_as_kpad_bias(mask[0], qq.shape[0], kk.shape[1])
                    if mask else None)
            # (B, L, H, D) -> (B, H, L, D)
            qq, kk, vv = (jnp.swapaxes(t, 1, 2) for t in (qq, kk, vv))
            out = flash_attention_bhld(qq, kk, vv, causal=is_causal,
                                       kpad_bias=kpad, dropout_p=p_eff,
                                       dropout_seed=seed, **blocks)
            return jnp.swapaxes(out, 1, 2)

        return apply_op(ffn, tuple(tensors))

    drop_key = None
    if p_eff > 0.0:
        from ...core import rng as _rng
        drop_key = _rng.next_key()

    def fn(qq, kk, vv, *mask):
        d = qq.shape[-1]
        scale = 1.0 / math.sqrt(d)
        # (B, L, H, D) -> (B, H, L, D)
        qq = jnp.swapaxes(qq, 1, 2)
        kk = jnp.swapaxes(kk, 1, 2)
        vv = jnp.swapaxes(vv, 1, 2)
        scores = jnp.einsum('bhld,bhmd->bhlm', qq, kk) * scale
        if mask:
            m = mask[0]
            if m.dtype == jnp.bool_:
                scores = jnp.where(m, scores, -1e30)
            else:
                scores = scores + m
        if is_causal:
            L, M = scores.shape[-2], scores.shape[-1]
            causal = jnp.tril(jnp.ones((L, M), dtype=bool))
            scores = jnp.where(causal, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        if drop_key is not None:
            keep = jax.random.bernoulli(drop_key, 1.0 - p_eff, probs.shape)
            probs = jnp.where(keep, probs / (1.0 - p_eff),
                              jnp.zeros_like(probs))
        out = jnp.einsum('bhlm,bhmd->bhld', probs, vv)
        return jnp.swapaxes(out, 1, 2)
    return apply_op(fn, tuple(tensors))


def multi_head_attention(query, key, value, num_heads, wq, wk, wv, wo,
                         bq=None, bk=None, bv=None, bo=None, attn_mask=None,
                         dropout_p=0.0, is_causal=False, cache=None,
                         training=True):
    """Functional MHA on (B, L, E) with (E, E) projection weights."""
    from .common import linear, dropout as _dropout
    q = linear(query, wq, bq)
    k = linear(key, wk, bk)
    v = linear(value, wv, bv)
    B, Lq, E = q.shape
    hd = E // num_heads
    q = q.reshape([B, Lq, num_heads, hd])
    k = k.reshape([B, k.shape[1], num_heads, hd])
    v = v.reshape([B, v.shape[1], num_heads, hd])
    if cache is not None:
        k = cache.append_k(k)
        v = cache.append_v(v)
    out = scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                       dropout_p=dropout_p, is_causal=is_causal,
                                       training=training)
    out = out.reshape([B, Lq, E])
    return linear(out, wo, bo)
