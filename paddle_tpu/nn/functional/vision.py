"""Vision functionals: grid_sample, affine_grid. Parity: nn/functional/vision.py."""
import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor, apply_op
from ...tensor._helpers import _t

__all__ = ['affine_grid', 'grid_sample']


def affine_grid(theta, out_shape, align_corners=True, name=None):
    theta = _t(theta)
    if isinstance(out_shape, Tensor):
        out_shape = out_shape.numpy().tolist()
    n, c, h, w = [int(s) for s in out_shape]

    def fn(th):
        if align_corners:
            xs = jnp.linspace(-1, 1, w)
            ys = jnp.linspace(-1, 1, h)
        else:
            xs = (jnp.arange(w) * 2 + 1) / w - 1
            ys = (jnp.arange(h) * 2 + 1) / h - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing='ij')
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # (h, w, 3)
        out = jnp.einsum('hwk,nik->nhwi', base, th)  # theta: (n, 2, 3)
        return out
    return apply_op(fn, (theta,))


def grid_sample(x, grid, mode='bilinear', padding_mode='zeros',
                align_corners=True, name=None):
    x, grid = _t(x), _t(grid)

    def fn(v, g):
        n, c, h, w = v.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def sample(ix, iy):
            inb = ((ix >= 0) & (ix < w) & (iy >= 0) & (iy < h))
            cx = jnp.clip(ix, 0, w - 1)
            cy = jnp.clip(iy, 0, h - 1)
            # v: (n,c,h,w); cx/cy: (n,gh,gw)
            out = v[jnp.arange(n)[:, None, None, None],
                    jnp.arange(c)[None, :, None, None],
                    cy[:, None, :, :], cx[:, None, :, :]]
            if padding_mode == 'zeros':
                out = out * inb[:, None, :, :].astype(v.dtype)
            return out

        if mode == 'nearest':
            return sample(jnp.round(fx).astype(jnp.int32),
                          jnp.round(fy).astype(jnp.int32))

        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = (fx - x0).astype(v.dtype)[:, None, :, :]
        wy = (fy - y0).astype(v.dtype)[:, None, :, :]
        v00 = sample(x0, y0)
        v01 = sample(x1, y0)
        v10 = sample(x0, y1)
        v11 = sample(x1, y1)
        return ((1 - wy) * ((1 - wx) * v00 + wx * v01) +
                wy * ((1 - wx) * v10 + wx * v11))
    return apply_op(fn, (x, grid))
