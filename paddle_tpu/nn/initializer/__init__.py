"""Parameter initializers + ParamAttr.

Parity: python/paddle/fluid/initializer.py and python/paddle/fluid/param_attr.py.
Each initializer is a pure function of (key, shape, dtype) — TPU-first so that
param init can itself be jitted/sharded at scale.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dtypes import convert_dtype, get_default_dtype
from ...core import rng as _rng

__all__ = ['Initializer', 'Constant', 'Uniform', 'Normal', 'TruncatedNormal',
           'XavierUniform', 'XavierNormal', 'KaimingUniform', 'KaimingNormal',
           'Assign', 'Bilinear', 'MSRA', 'Xavier', 'NumpyArrayInitializer',
           'ConstantInitializer', 'UniformInitializer', 'NormalInitializer',
           'TruncatedNormalInitializer', 'XavierInitializer', 'MSRAInitializer',
           'BilinearInitializer', 'ParamAttr', 'calculate_gain', 'set_global_initializer']


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels, paddle layout (cout, cin, *k) or our NHWC (k, k, cin, cout):
    receptive = int(np.prod(shape[:-2])) if len(shape) > 2 else 1
    fan_in = shape[-2] * receptive
    fan_out = shape[-1] * receptive
    return fan_in, fan_out


def calculate_gain(nonlinearity, param=None):
    gains = {'sigmoid': 1.0, 'linear': 1.0, 'conv1d': 1.0, 'conv2d': 1.0,
             'conv3d': 1.0, 'tanh': 5.0 / 3, 'relu': math.sqrt(2.0),
             'leaky_relu': math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             'selu': 3.0 / 4}
    return gains.get(nonlinearity, 1.0)


class Initializer:
    def __call__(self, shape, dtype=None, key=None):
        dtype = convert_dtype(dtype) or get_default_dtype()
        if key is None:
            key = _rng.next_key()
        return self.generate(key, tuple(int(s) for s in shape), dtype)

    def generate(self, key, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self._value = value

    def generate(self, key, shape, dtype):
        return jnp.full(shape, self._value, dtype=dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self._low, self._high = low, high

    def generate(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype=dtype,
                                  minval=self._low, maxval=self._high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, seed=0):
        self._mean, self._std = mean, std

    def generate(self, key, shape, dtype):
        return self._mean + self._std * jax.random.normal(key, shape, dtype=dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, seed=0):
        self._mean, self._std = mean, std

    def generate(self, key, shape, dtype):
        return self._mean + self._std * jax.random.truncated_normal(
            key, -2.0, 2.0, shape, dtype=dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, seed=0):
        self._fan_in, self._fan_out, self._gain = fan_in, fan_out, gain

    def generate(self, key, shape, dtype):
        fi, fo = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self._gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(key, shape, dtype=dtype, minval=-limit, maxval=limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, seed=0):
        self._fan_in, self._fan_out, self._gain = fan_in, fan_out, gain

    def generate(self, key, shape, dtype):
        fi, fo = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self._gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(key, shape, dtype=dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu', seed=0):
        self._fan_in = fan_in
        self._gain = calculate_gain(nonlinearity, negative_slope)

    def generate(self, key, shape, dtype):
        fi, _ = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        limit = self._gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(key, shape, dtype=dtype, minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu', seed=0):
        self._fan_in = fan_in
        self._gain = calculate_gain(nonlinearity, negative_slope)

    def generate(self, key, shape, dtype):
        fi, _ = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        std = self._gain / math.sqrt(fi)
        return std * jax.random.normal(key, shape, dtype=dtype)


class Assign(Initializer):
    def __init__(self, value):
        self._value = np.asarray(value)

    def generate(self, key, shape, dtype):
        v = jnp.asarray(self._value, dtype=dtype)
        if tuple(v.shape) != tuple(shape):
            v = v.reshape(shape)
        return v


class Bilinear(Initializer):
    """For upsampling deconv kernels (ref: initializer.py:BilinearInitializer)."""
    def generate(self, key, shape, dtype):
        # shape: (kh, kw, cin, cout) NHWC-style or (cout, cin, kh, kw)
        w = np.zeros(shape, dtype=np.float32)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects 4-D weights")
        kh, kw = (shape[0], shape[1]) if shape[0] <= shape[2] else (shape[2], shape[3])
        # operate on a canonical (kh, kw) filter then broadcast
        f = np.zeros((kh, kw), dtype=np.float32)
        factor = (kh + 1) // 2
        center = (factor - 1) if kh % 2 == 1 else (factor - 0.5)
        og = np.ogrid[:kh, :kw]
        f = (1 - abs(og[0] - center) / factor) * (1 - abs(og[1] - center) / factor)
        if shape[0] == kh:  # (kh, kw, cin, cout)
            w[:, :, :, :] = f[:, :, None, None]
        else:  # (cout, cin, kh, kw)
            w[:, :, :, :] = f[None, None, :, :]
        return jnp.asarray(w, dtype=dtype)


# fluid-era aliases
ConstantInitializer = Constant
UniformInitializer = Uniform
NormalInitializer = Normal
TruncatedNormalInitializer = TruncatedNormal
Xavier = XavierUniform
XavierInitializer = XavierUniform
MSRA = KaimingNormal
MSRAInitializer = KaimingNormal
BilinearInitializer = Bilinear
NumpyArrayInitializer = Assign

_global_weight_init = [None]
_global_bias_init = [None]


def set_global_initializer(weight_init, bias_init=None):
    _global_weight_init[0] = weight_init
    _global_bias_init[0] = bias_init


def global_weight_initializer():
    return _global_weight_init[0]


def global_bias_initializer():
    return _global_bias_init[0]


class ParamAttr:
    """Parity: python/paddle/fluid/param_attr.py:ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else False
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        raise TypeError(f"Invalid param attr: {arg!r}")


WeightNormParamAttr = ParamAttr  # placeholder refined in utils.weight_norm
