"""Activation layers. Parity: python/paddle/nn/layer/activation.py."""
from ..layer_base import Layer
from ..initializer import Constant
from .. import functional as F


def _simple(fname, **fixed):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._kwargs = dict(fixed)
            sig = _SIGS.get(fname, ())
            for name, val in zip(sig, args):
                self._kwargs[name] = val
            for k, v in kwargs.items():
                if k != 'name':
                    self._kwargs[k] = v

        def forward(self, x):
            return getattr(F, fname)(x, **self._kwargs)
    _Act.__name__ = fname
    return _Act


_SIGS = {
    'leaky_relu': ('negative_slope',),
    'elu': ('alpha',),
    'celu': ('alpha',),
    'gelu': ('approximate',),
    'hardshrink': ('threshold',),
    'hardtanh': ('min', 'max'),
    'hardsigmoid': ('slope', 'offset'),
    'softplus': ('beta', 'threshold'),
    'softshrink': ('threshold',),
    'thresholded_relu': ('threshold',),
    'log_softmax': ('axis',),
    'softmax': ('axis',),
    'maxout': ('groups', 'axis'),
    'glu': ('axis',),
}

ReLU = _simple('relu')
ReLU6 = _simple('relu6')
LeakyReLU = _simple('leaky_relu')
ELU = _simple('elu')
CELU = _simple('celu')
GELU = _simple('gelu')
Sigmoid = _simple('sigmoid')
Hardsigmoid = _simple('hardsigmoid')
Hardswish = _simple('hardswish')
Hardshrink = _simple('hardshrink')
Hardtanh = _simple('hardtanh')
Softplus = _simple('softplus')
Softshrink = _simple('softshrink')
Softsign = _simple('softsign')
Swish = _simple('swish')
Silu = _simple('silu')
Mish = _simple('mish')
Tanh = _simple('tanh')
Tanhshrink = _simple('tanhshrink')
ThresholdedReLU = _simple('thresholded_relu')
LogSigmoid = _simple('log_sigmoid')
LogSoftmax = _simple('log_softmax')
Softmax = _simple('softmax')
Maxout = _simple('maxout')
GLU = _simple('glu')
SELU = _simple('selu')


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            shape=[num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1. / 8., upper=1. / 3., name=None):
        super().__init__()
        self._lower = lower
        self._upper = upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, training=self.training)
