"""Conv layers. Parity: python/paddle/nn/layer/conv.py."""
import numpy as np

from ..layer_base import Layer
from ..initializer import KaimingUniform, Uniform
from .. import functional as F


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, transposed,
                 dims, stride=1, padding=0, output_padding=0, dilation=1,
                 groups=1, padding_mode='zeros', weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__()
        if groups <= 0 or in_channels % groups or out_channels % groups:
            raise ValueError("invalid groups for conv")
        self._in_channels = in_channels
        self._out_channels = out_channels
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else \
            [kernel_size] * dims
        self._kernel_size = list(ks)
        self._stride = stride
        self._padding = padding
        self._output_padding = output_padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._transposed = transposed
        if transposed:
            w_shape = [in_channels, out_channels // groups] + list(ks)
        else:
            w_shape = [out_channels, in_channels // groups] + list(ks)
        fan_in = in_channels // groups * int(np.prod(ks))
        self.weight = self.create_parameter(
            shape=w_shape, attr=weight_attr,
            default_initializer=KaimingUniform(fan_in=fan_in, nonlinearity='leaky_relu',
                                               negative_slope=np.sqrt(5.0)))
        bound = 1.0 / np.sqrt(fan_in)
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode='zeros',
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, False, 1,
                         stride, padding, 0, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode='zeros',
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, False, 2,
                         stride, padding, 0, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode='zeros',
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, False, 3,
                         stride, padding, 0, dilation, groups, padding_mode,
                         weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, True, 1,
                         stride, padding, output_padding, dilation, groups,
                         'zeros', weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, True, 2,
                         stride, padding, output_padding, dilation, groups,
                         'zeros', weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, True, 3,
                         stride, padding, output_padding, dilation, groups,
                         'zeros', weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)
