"""Distance layers. Parity: python/paddle/nn/layer/distance.py."""
import jax.numpy as jnp

from ..layer_base import Layer
from ...core.tensor import apply_op
from ...tensor._helpers import _t


class PairwiseDistance(Layer):
    def __init__(self, p=2., epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        p, eps, keep = self.p, self.epsilon, self.keepdim
        return apply_op(
            lambda a, b: jnp.linalg.norm(a - b + eps, ord=p, axis=-1,
                                         keepdims=keep),
            (_t(x), _t(y)))
