"""Norm layers. Parity: python/paddle/nn/layer/norm.py."""
import numpy as np
import jax.numpy as jnp

from ..layer_base import Layer
from ..initializer import Constant
from .. import functional as F
from ...core.tensor import Tensor


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)
        self.register_buffer('_mean', Tensor(jnp.zeros([num_features])))
        self.register_buffer('_variance', Tensor(jnp.ones([num_features])))

    def forward(self, input):
        return F.batch_norm(input, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """fluid-era BatchNorm (act fused). Ref: fluid/dygraph/nn.py:BatchNorm."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, dtype='float32',
                 data_layout='NCHW', in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, input):
        out = super().forward(input)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCL',
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         'NCHW' if data_format in ('NCL', 'NC') else 'NHWC',
                         use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCDHW',
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         'NCHW' if data_format == 'NCDHW' else 'NHWC',
                         use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN: stats psum'd over the data-parallel mesh axis when
    running inside shard_map/pjit. Ref: nn/layer/norm.py:SyncBatchNorm (NCCL)."""

    def forward(self, input):
        from ...distributed import env as dist_env
        axis = dist_env.current_data_axis()
        if axis is None or not self.training:
            return super().forward(input)
        from ...core.tensor import apply_op
        x = input
        shp = [1] * x.ndim
        ch_axis = 1 if self._data_format.startswith('NC') else x.ndim - 1
        shp[ch_axis] = self._num_features
        reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
        eps, momentum = self._epsilon, self._momentum
        rm, rv = self._mean, self._variance
        tensors = [x] + ([self.weight, self.bias] if self.weight is not None else [])

        tensors += [rm, rv]

        def fn(v, *rest):
            import jax
            wb, (m0, v0) = rest[:-2], rest[-2:]
            n_local = np.prod([v.shape[i] for i in reduce_axes])
            s = jnp.sum(v, axis=reduce_axes)
            ss = jnp.sum(v * v, axis=reduce_axes)
            s = jax.lax.psum(s, axis)
            ss = jax.lax.psum(ss, axis)
            n = jax.lax.psum(jnp.asarray(n_local, v.dtype), axis)
            mean = s / n
            var = ss / n - mean * mean
            out = (v - mean.reshape(shp)) / jnp.sqrt(var.reshape(shp) + eps)
            if wb:
                out = out * wb[0].reshape(shp) + wb[1].reshape(shp)
            new_rm = momentum * m0 + (1 - momentum) * mean.astype(m0.dtype)
            new_rv = momentum * v0 + (1 - momentum) * var.astype(v0.dtype)
            return out, new_rm, new_rv
        out, new_rm, new_rv = apply_op(fn, tuple(tensors), n_outputs=3)
        from ...core.autograd import no_grad
        with no_grad():
            rm._inplace_value(new_rm._value)
            rv._inplace_value(new_rv._value)
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon, data_format=layer._data_format)
            if layer.weight is not None:
                out.weight = layer.weight
                out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in layer.named_children():
            new_sub = cls.convert_sync_batchnorm(sub)
            if new_sub is not sub:
                out.add_sublayer(name, new_sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.layer_norm(input, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            shape=[hidden_size], attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, input):
        return F.rms_norm(input, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCHW', name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                shape=[num_channels], attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                shape=[num_channels], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.group_norm(input, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                shape=[num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter(
                shape=[num_features], attr=bias_attr, is_bias=True)

    def forward(self, input):
        return F.instance_norm(input, weight=self.weight, bias=self.bias,
                               eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, input):
        return F.local_response_norm(input, self.size, self.alpha, self.beta,
                                     self.k, self.data_format)


class SpectralNorm(Layer):
    """Power-iteration spectral norm of a weight tensor.

    Ref: fluid/dygraph/nn.py:SpectralNorm."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype='float32'):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        import jax
        from ...core import rng as _rng
        self.register_buffer('weight_u', Tensor(
            jax.random.normal(_rng.next_key(), (h,), dtype=jnp.float32)))
        self.register_buffer('weight_v', Tensor(
            jax.random.normal(_rng.next_key(), (w,), dtype=jnp.float32)))

    def forward(self, weight):
        from ...core.tensor import apply_op
        dim, iters, eps = self._dim, self._power_iters, self._eps
        u0, v0 = self.weight_u, self.weight_v

        def fn(w, u, v):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma, u, v
        out, u, v = apply_op(fn, (weight, u0, v0), n_outputs=3)
        from ...core.autograd import no_grad
        with no_grad():
            u0._inplace_value(u._value)
            v0._inplace_value(v._value)
        return out
