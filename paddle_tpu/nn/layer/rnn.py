"""RNN layers. Parity: python/paddle/nn/layer/rnn.py.

TPU-first: the time loop is lax.scan (static trip count, XLA-pipelined); cells
are pure functions over raw arrays shared by eager and scan paths.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..layer_base import Layer
from ..initializer import Uniform
from .. import functional as F
from ..functional.rnn import rnn_scan
from ...core.tensor import Tensor
from ...tensor._helpers import _t
from ...core.tensor import apply_op


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype='float32',
                           init_value=0., batch_dim_idx=0):
        batch = _t(batch_ref).shape[batch_dim_idx]
        hs = self.state_shape
        if isinstance(hs[0], (list, tuple)):
            return tuple(Tensor(jnp.full((batch,) + tuple(s), init_value,
                                         dtype=jnp.float32)) for s in hs)
        return Tensor(jnp.full((batch,) + tuple(hs), init_value,
                               dtype=jnp.float32))


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def _params(self):
        return (self.weight_ih, self.weight_hh, self.bias_ih, self.bias_hh)

    def cell_fn(self, state, x_t, w_ih, w_hh, b_ih, b_hh):
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        h = act(x_t @ w_ih.T + b_ih + state @ w_hh.T + b_hh)
        return h, h

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = apply_op(lambda x, h, *p: self.cell_fn(h, x, *p)[0],
                       (inputs, states) + self._params())
        return out, out


class LSTMCell(SimpleRNNCell):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        Layer.__init__(self)
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def cell_fn(self, state, x_t, w_ih, w_hh, b_ih, b_hh):
        h, c = state
        gates = x_t @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states
        outs = apply_op(
            lambda x, h, c, *p: (lambda r: (r[0][0], r[0][1]))(
                self.cell_fn((h, c), x, *p)),
            (inputs, h0, c0) + self._params(), n_outputs=2)
        h, c = outs
        return h, (h, c)


class GRUCell(SimpleRNNCell):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        Layer.__init__(self)
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def cell_fn(self, state, x_t, w_ih, w_hh, b_ih, b_hh):
        h = state
        x_proj = x_t @ w_ih.T + b_ih
        h_proj = h @ w_hh.T + b_hh
        xr, xz, xn = jnp.split(x_proj, 3, axis=-1)
        hr, hz, hn = jnp.split(h_proj, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, h_new

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        out = apply_op(lambda x, h, *p: self.cell_fn(h, x, *p)[0],
                       (inputs, states) + self._params())
        return out, out


class RNN(Layer):
    """Run any cell over time. Parity: nn/layer/rnn.py:RNN."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            ref = inputs if not self.time_major else _t(inputs).transpose([1, 0, 2])
            initial_states = self.cell.get_initial_states(ref)
        outs, final = rnn_scan(self.cell.cell_fn, inputs, initial_states,
                               time_major=self.time_major,
                               reverse=self.is_reverse,
                               sequence_length=sequence_length,
                               extra_params=self.cell._params())
        return outs, final


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        fw_st = bw_st = None
        if initial_states is not None:
            fw_st, bw_st = initial_states
        out_f, st_f = self.rnn_fw(inputs, fw_st, sequence_length)
        out_b, st_b = self.rnn_bw(inputs, bw_st, sequence_length)
        from ...tensor.manipulation import concat
        return concat([out_f, out_b], axis=-1), (st_f, st_b)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, activation="tanh"):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        self.direction = direction

        def make_cell(isz):
            kw = dict(weight_ih_attr=weight_ih_attr, weight_hh_attr=weight_hh_attr,
                      bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
            if mode == "LSTM":
                return LSTMCell(isz, hidden_size, **kw)
            if mode == "GRU":
                return GRUCell(isz, hidden_size, **kw)
            return SimpleRNNCell(isz, hidden_size, activation=activation, **kw)

        from .container import LayerList
        self._all_layers = LayerList()
        for i in range(num_layers):
            isz = input_size if i == 0 else hidden_size * self.num_directions
            if bidirect:
                self._all_layers.append(BiRNN(make_cell(isz), make_cell(isz),
                                              time_major))
            else:
                self._all_layers.append(RNN(make_cell(isz), False, time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        out = inputs
        final_h, final_c = [], []
        for i, layer in enumerate(self._all_layers):
            init = None
            if initial_states is not None:
                init = self._slice_states(initial_states, i)
            out, st = layer(out, init, sequence_length)
            if i < self.num_layers - 1 and self.dropout > 0:
                out = F.dropout(out, p=self.dropout, training=self.training)
            self._collect(st, final_h, final_c)
        from ...tensor.manipulation import stack
        if self.mode == "LSTM":
            return out, (stack(final_h, 0), stack(final_c, 0))
        return out, stack(final_h, 0)

    def _slice_states(self, initial_states, i):
        d = self.num_directions

        def pick(s, idx):
            return s[idx]
        if self.mode == "LSTM":
            h, c = initial_states
            if d == 2:
                return ((pick(h, 2 * i), pick(c, 2 * i)),
                        (pick(h, 2 * i + 1), pick(c, 2 * i + 1)))
            return (pick(h, i), pick(c, i))
        h = initial_states
        if d == 2:
            return (pick(h, 2 * i), pick(h, 2 * i + 1))
        return pick(h, i)

    def _collect(self, st, final_h, final_c):
        if self.num_directions == 2:
            st_f, st_b = st
            for s in (st_f, st_b):
                if self.mode == "LSTM":
                    final_h.append(s[0])
                    final_c.append(s[1])
                else:
                    final_h.append(s)
        else:
            if self.mode == "LSTM":
                final_h.append(st[0])
                final_c.append(st[1])
            else:
                final_h.append(st)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.,
                 activation="tanh", **kwargs):
        super().__init__("RNN", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, activation=activation, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0., **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0., **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)
