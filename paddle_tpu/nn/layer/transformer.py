"""Transformer layers. Parity: python/paddle/nn/layer/transformer.py.

TPU-first: attention goes through F.scaled_dot_product_attention which
auto-dispatches to the Pallas flash kernel for long causal sequences.
"""
import collections

import numpy as np
import jax.numpy as jnp

from ..layer_base import Layer
from ..initializer import XavierUniform
from .. import functional as F
from .common import Linear, Dropout
from .norm import LayerNorm
from .container import LayerList
from ...core.tensor import Tensor
from ...tensor.manipulation import concat


def _convert_attn_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    if np.dtype(attn_mask.dtype) == np.bool_:
        return attn_mask
    return attn_mask.astype(dtype)


class MultiHeadAttention(Layer):
    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0., kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _prepare_qkv(self, query, key, value, cache=None):
        B = query.shape[0]
        q = self.q_proj(query).reshape([B, -1, self.num_heads, self.head_dim])
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self.k_proj(key).reshape([B, -1, self.num_heads, self.head_dim])
            v = self.v_proj(value).reshape([B, -1, self.num_heads, self.head_dim])
        if isinstance(cache, self.Cache):
            k = concat([cache.k, k], axis=1)
            v = concat([cache.v, v], axis=1)
            cache = self.Cache(k, v)
        return q, k, v, cache

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            B = key.shape[0]
            k = self.k_proj(key).reshape([B, -1, self.num_heads, self.head_dim])
            v = self.v_proj(value if value is not None else key).reshape(
                [B, -1, self.num_heads, self.head_dim])
            return self.StaticCache(k, v)
        B = key.shape[0]
        z = Tensor(jnp.zeros([B, 0, self.num_heads, self.head_dim]))
        return self.Cache(z, z)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q, k, v, cache = self._prepare_qkv(query, key, value, cache)
        mask = _convert_attn_mask(attn_mask, query.dtype)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=mask, dropout_p=self.dropout if self.training else 0.,
            training=self.training)
        B = query.shape[0]
        out = out.reshape([B, -1, self.embed_dim])
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(None)  # weights not materialized on the flash path
        if cache is not None:
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, cache = self.self_attn(src, src, src, src_mask, cache)
        src = self._sublayer_out(src, residual, self.dropout1, self.norm1)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = self._sublayer_out(src, residual, self.dropout2, self.norm2)
        return src if cache is None else (src, cache)

    def _sublayer_out(self, src, residual, drop, norm):
        """Post-norm epilogue: norm(residual + dropout(src)) rides the fused
        pallas kernel on TPU; pre-norm keeps the composed form."""
        if not self.normalize_before:
            return F.fused_dropout_add_layer_norm(
                src, residual, norm.weight, norm.bias, dropout_p=drop.p,
                epsilon=norm._epsilon, training=self.training)
        return residual + drop(src)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] +
                                [_clone_layer(encoder_layer)
                                 for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, new_cache = mod(output, src_mask, cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, dropout=attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incr_cache = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(tgt)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt, static_cache = self.cross_attn(tgt, memory, memory, memory_mask,
                                                cache[1])
        tgt = residual + self.dropout2(tgt)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incr_cache, static_cache))

    def gen_cache(self, memory):
        incremental_cache = self.self_attn.gen_cache(memory)
        static_cache = self.cross_attn.gen_cache(
            memory, memory, MultiHeadAttention.StaticCache)
        return incremental_cache, static_cache


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] +
                                [_clone_layer(decoder_layer)
                                 for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask, memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask, memory_mask,
                                        cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        return [layer.gen_cache(memory) for layer in self.layers]


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            enc_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(enc_layer, num_encoder_layers,
                                              enc_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            dec_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dec_layer, num_decoder_layers,
                                              dec_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        return Tensor((jnp.tril(jnp.ones((length, length))) - 1) * 1e9)


def _clone_layer(layer):
    """Fresh re-init clone (structure copy with new params)."""
    import copy
    new = copy.deepcopy(layer)
    # re-draw parameters so clones don't share init values
    from ...core import rng as _rng
    for p in new.parameters():
        import jax
        noise_key = _rng.next_key()
        shape = tuple(p.shape)
        fan = shape[0] if shape else 1
        limit = float(np.sqrt(6.0 / (sum(shape) if shape else 1)))
        if len(shape) >= 2:
            p._inplace_value(jax.random.uniform(
                noise_key, shape, dtype=p._value.dtype, minval=-limit,
                maxval=limit))
    return new
