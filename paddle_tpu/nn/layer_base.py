"""nn.Layer: the module system.

Parity: python/paddle/fluid/dygraph/layers.py (Layer: parameters, sublayers,
state_dict, hooks, train/eval). TPU-first addition: ``functional_call`` runs a
layer with substituted parameter/buffer values and returns collected buffer
updates — the bridge from stateful modules to pure functions that jax.jit /
jax.grad / pjit can transform.
"""
import collections

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core.dtypes import convert_dtype, get_default_dtype
from ..core import rng as _rng
from ..utils.unique_name import generate as _uname
from .initializer import (ParamAttr, Constant, XavierUniform,
                          global_weight_initializer, global_bias_initializer)


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype='float32'):
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._full_name = _uname(name_scope or
                                 self.__class__.__name__.lower())
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._casted_dtype = None

    # -- naming -------------------------------------------------------------
    def full_name(self):
        return self._full_name

    # -- train/eval ---------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # -- parameter creation -------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            ginit = global_bias_initializer() if is_bias else global_weight_initializer()
            init = ginit or (Constant(0.0) if is_bias else XavierUniform())
        value = init(shape, dtype=dtype)
        name = attr.name or _uname(self._full_name + ('.b' if is_bias else '.w'))
        p = Parameter(value, name=name, trainable=attr.trainable,
                      regularizer=attr.regularizer,
                      learning_rate=attr.learning_rate,
                      need_clip=attr.need_clip)
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError(f"add_parameter expects Parameter, got {type(parameter)}")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # -- attribute magic ----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get('_parameters')
        layers = self.__dict__.get('_sub_layers')
        buffers = self.__dict__.get('_buffers')
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() before assigning params")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
        elif buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
            else:
                object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and value is None:
                params[name] = None
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ('_parameters', '_sub_layers', '_buffers'):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ('_parameters', '_sub_layers', '_buffers'):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = []
        for store in ('_parameters', '_sub_layers', '_buffers'):
            extra += list(self.__dict__.get(store, {}).keys())
        return super().__dir__() + extra

    # -- traversal ----------------------------------------------------------
    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix='', include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, l in self.named_children():
            if l is None or id(l) in layers_set:
                continue
            layers_set.add(id(l))
            sub_prefix = prefix + ('.' if prefix else '') + name
            yield sub_prefix, l
            yield from l.named_sublayers(prefix=sub_prefix, include_self=False,
                                         layers_set=layers_set)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix='', include_sublayers=True):
        seen = set()
        layers = [(prefix, self)]
        if include_sublayers:
            layers += [(prefix + ('.' if prefix else '') + n, l)
                       for n, l in self.named_sublayers()]
        for lp, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (lp + ('.' if lp else '') + name, p)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix='', include_sublayers=True):
        seen = set()
        layers = [(prefix, self)]
        if include_sublayers:
            layers += [(prefix + ('.' if prefix else '') + n, l)
                       for n, l in self.named_sublayers()]
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (lp + ('.' if lp else '') + name, b)

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix='', use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip('.'),
                                             include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(prefix=structured_name_prefix.rstrip('.'),
                                          include_sublayers=include_sublayers):
            shortname = name.rsplit('.', 1)[-1]
            owner = self._find_owner(name)
            if owner is not None and shortname in owner._non_persistable_buffer_names:
                continue
            dest[name] = b
        return dest

    def _find_owner(self, qualified_name):
        parts = qualified_name.split('.')[:-1]
        layer = self
        for p in parts:
            if p in layer._sub_layers:
                layer = layer._sub_layers[p]
            else:
                return None
        return layer

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        matched = {}
        for k, v in state_dict.items():
            if k in own:
                matched[k] = v
            else:
                unexpected.append(k)
        for k in own:
            if k not in matched:
                missing.append(k)
        for k, v in matched.items():
            t = own[k]
            val = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            if tuple(val.shape) != tuple(t._value.shape):
                raise ValueError(
                    f"shape mismatch for {k}: loaded {list(val.shape)} vs "
                    f"{list(t._value.shape)}")
            t._inplace_value(val.astype(t.dtype))
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / device movement --------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = convert_dtype(dtype)
            for t in list(self.parameters()) + list(self.buffers()):
                from ..core.dtypes import is_floating
                if is_floating(t.dtype):
                    t._inplace_value(t._value.astype(dt))
            self._dtype = dt
        if device is not None:
            import jax
            from ..core.place import CPUPlace, TPUPlace, Place
            if isinstance(device, str):
                from ..core import place as place_mod
                name, _, idx = device.partition(':')
                plc = (CPUPlace if name == 'cpu' else TPUPlace)(int(idx or 0))
            elif isinstance(device, Place):
                plc = device
            else:
                plc = None
            if plc is not None:
                dev = plc.jax_device()
                if dev is not None:
                    for t in list(self.parameters()) + list(self.buffers()):
                        t._inplace_value(jax.device_put(t._value, dev))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype='float32')

    def half(self):
        return self.to(dtype='float16')

    def bfloat16(self):
        return self.to(dtype='bfloat16')

    # -- hooks & call -------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        helper = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[helper._id] = hook
        return helper

    def register_forward_post_hook(self, hook):
        helper = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[helper._id] = hook
        return helper

    def __call__(self, *inputs, **kwargs):
        from ..core.tensor import capture_watch
        w = capture_watch()
        if w is not None:
            w.note_layer(self)
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ''

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self.named_children():
            body = repr(l).split('\n')
            body = [body[0]] + ['  ' + b for b in body[1:]]
            lines.append(f"  ({name}): " + '\n'.join(body))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + '\n' + '\n'.join(lines) + '\n)'
        return main + ')'

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


def functional_call(layer, state, *args, **kwargs):
    """Run ``layer`` with parameter/buffer payloads from ``state``.

    state: dict of qualified-name -> raw value (jax array or Tensor).
    Returns (output, new_buffer_values) where new_buffer_values holds the
    post-call payloads of all persistable buffers (e.g. BN running stats).
    """
    own = layer.state_dict()
    buffer_names = [n for n, _ in layer.named_buffers()]
    originals = {}
    try:
        for name, val in state.items():
            t = own.get(name)
            if t is None:
                continue
            originals[name] = t._value
            t._value = val._value if isinstance(val, Tensor) else val
        out = layer(*args, **kwargs)
        new_buffers = {n: b._value for n, b in layer.named_buffers()
                       if n in state or n in own}
    finally:
        for name, v in originals.items():
            own[name]._value = v
    return out, new_buffers


def state_values(layer):
    """state_dict as raw jax values (a pytree for jit/grad)."""
    return {k: v._value for k, v in layer.state_dict().items()}


def param_values(layer, trainable_only=True):
    return {k: p._value for k, p in layer.named_parameters()
            if (p.trainable if trainable_only else True)}


def buffer_values(layer):
    return {k: b._value for k, b in layer.named_buffers()}


def load_state_values(layer, values):
    own = layer.state_dict()
    for k, v in values.items():
        if k in own:
            own[k]._inplace_value(v)
