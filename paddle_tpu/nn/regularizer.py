"""Weight-decay regularizers. Parity: python/paddle/fluid/regularizer.py."""


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def loss(self, param):
        raise NotImplementedError

    def grad_term(self, param_value):
        """Gradient contribution added to the raw grad (decay applied in-grad,
        matching the reference's append_regularization_ops)."""
        raise NotImplementedError


class L2Decay(WeightDecayRegularizer):
    def loss(self, param):
        return self._coeff * 0.5 * (param * param).sum()

    def grad_term(self, param_value):
        return self._coeff * param_value

    def __repr__(self):
        return f"L2Decay(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    def loss(self, param):
        return self._coeff * param.abs().sum()

    def grad_term(self, param_value):
        import jax.numpy as jnp
        return self._coeff * jnp.sign(param_value)

    def __repr__(self):
        return f"L1Decay(coeff={self._coeff})"


# fluid aliases
L1DecayRegularizer = L1Decay
L2DecayRegularizer = L2Decay
