"""nn.utils: weight_norm / spectral_norm wrappers.

Parity: python/paddle/nn/utils/weight_norm_hook.py.
"""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor, apply_op


def _norm_except(v, dim):
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


def weight_norm(layer, name='weight', dim=0):
    """Reparameterize layer.<name> = g * v / ||v|| via a forward-pre-hook."""
    w = getattr(layer, name)
    g_init = np.asarray(_norm_except(w._value, dim))
    v = Parameter(w._value, name=(w.name or name) + '_v')
    g = Parameter(jnp.asarray(g_init), name=(w.name or name) + '_g')
    del layer._parameters[name]
    layer.add_parameter(name + '_v', v)
    layer.add_parameter(name + '_g', g)

    def hook(l, inputs):
        vv, gg = l._parameters[name + '_v'], l._parameters[name + '_g']
        new_w = apply_op(
            lambda a, b: b * a / jnp.maximum(_norm_except(a, dim), 1e-12),
            (vv, gg))
        object.__setattr__(l, name, new_w)
        return None

    handle = layer.register_forward_pre_hook(hook)
    layer._weight_norm_handle = handle
    hook(layer, ())
    return layer


def remove_weight_norm(layer, name='weight'):
    if hasattr(layer, '_weight_norm_handle'):
        layer._weight_norm_handle.remove()
    v = layer._parameters.pop(name + '_v')
    g = layer._parameters.pop(name + '_g')
    w_val = np.asarray(g._value) * np.asarray(v._value) / np.maximum(
        np.asarray(_norm_except(v._value, 0)), 1e-12)
    layer.add_parameter(name, Parameter(jnp.asarray(w_val), name=name))
    return layer


def spectral_norm(layer, name='weight', n_power_iterations=1, eps=1e-12, dim=None):
    from .layer.norm import SpectralNorm as _SN
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = _SN(list(w.shape), dim=dim, power_iters=n_power_iterations, eps=eps)
    layer.add_sublayer(name + '_sn', sn)
    orig = layer._parameters.pop(name)
    layer.add_parameter(name + '_orig', orig)

    def hook(l, inputs):
        new_w = sn(l._parameters[name + '_orig'])
        object.__setattr__(l, name, new_w)
        return None

    layer.register_forward_pre_hook(hook)
    hook(layer, ())
    return layer
