"""paddle_tpu.observability: one telemetry spine for the whole runtime.

Three cooperating pieces (docs/OBSERVABILITY.md has the operator guide):

- a process-wide **metrics registry** (``counter``/``gauge``/``histogram``)
  with JSONL **step-event** export and Prometheus-style text exposition;
- a **span tracer** emitting Chrome trace-event JSON (Perfetto-loadable)
  that bridges into ``jax.profiler.TraceAnnotation`` while a device trace is
  active, with a sampled ``block_until_ready`` discipline;
- **interposed counters** for jit retraces/compiles (via ``jax.monitoring``)
  and host-transfer bytes (``Tensor.numpy()``, Executor fetches).

Built-in instrumentation rides the narrow waists: ``Executor.run`` (program
cache, verify/compile time), ``hapi.Model.fit`` (``TelemetryCallback``),
``io.DataLoader`` / ``reader.buffered`` (queue depth, wait time),
``optimizer.step``, the resilience layer (NaN skips, retries, checkpoint
durations), and ``distributed.collective``.

MISSION CONTROL layers cluster-wide operation on the same spine
(docs/OBSERVABILITY.md, "Mission control"): per-rank telemetry flushed
live into the supervisor's run dir (``flush``), merged into one cluster
snapshot + a one-lane-per-rank Perfetto trace (``aggregate``), served over
a localhost HTTP endpoint — ``/metrics`` Prometheus exposition,
``/healthz``, ``/events``, ``/diagnosis`` (``endpoint``) — and diagnosed
by streaming anomaly detectors that name stragglers, retrace storms,
input-bound runs, and serving overload with fix-it hints (``doctor``).

Everything is off (near-zero overhead: one flag check per site) until
``PADDLE_TPU_TELEMETRY=1`` or an explicit ``observability.enable()``.

This package is imported by ``core.tensor`` at interpreter start: modules
here must stay stdlib-only at import time (jax strictly lazy) and must not
import other ``paddle_tpu`` modules at the top level.
"""
from . import events as _events
from . import interpose, registry, spans, state, timing  # noqa: F401
from . import aggregate, doctor, endpoint, flush  # noqa: F401  mission ctl
from . import costs, flight, slo  # noqa: F401  cost explorer + black box
from . import baseline, timeseries  # noqa: F401  time series + sentinel
from .state import enable, disable, enabled, log_dir, sync_every
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       get_registry, counter, gauge, histogram, snapshot,
                       to_prometheus)
from .registry import reset as reset_metrics
from .spans import (span, Span, dump_chrome_trace, trace_events,
                    async_begin, async_instant, async_end)
from .timing import Stopwatch, timer
from .interpose import (install_jax_hooks, record_host_transfer,
                        record_collective)
from .interpose import summary as counters_summary
from .flush import start_rank_flusher, stop_rank_flusher
from .endpoint import MetricsServer
from .doctor import diagnose, run_doctor

# event-log surface (module name 'events' is kept for the submodule; the
# buffered-event accessor is exported as event_log to avoid shadowing it)
event = _events.emit
event_log = _events.events
dump_jsonl = _events.dump_jsonl
set_sink = _events.set_sink
close_sink = _events.close_sink
wall_ts = _events.wall_ts

__all__ = [
    'enable', 'disable', 'enabled', 'log_dir', 'sync_every',
    'Counter', 'Gauge', 'Histogram', 'MetricsRegistry', 'get_registry',
    'counter', 'gauge', 'histogram', 'snapshot', 'to_prometheus',
    'reset_metrics', 'reset',
    'span', 'Span', 'dump_chrome_trace', 'trace_events',
    'async_begin', 'async_instant', 'async_end',
    'event', 'event_log', 'dump_jsonl', 'set_sink', 'close_sink', 'wall_ts',
    'Stopwatch', 'timer',
    'install_jax_hooks', 'record_host_transfer', 'record_collective',
    'counters_summary', 'TelemetryCallback',
    # mission control (docs/OBSERVABILITY.md, "Mission control")
    'aggregate', 'doctor', 'endpoint', 'flush',
    'start_rank_flusher', 'stop_rank_flusher', 'MetricsServer',
    'diagnose', 'run_doctor',
    # cost explorer + SLO tracker + flight recorder
    'costs', 'slo', 'flight',
    # time series + cross-run regression sentinel
    'baseline', 'timeseries',
]


def reset():
    """Clear every buffer (metrics, events, spans, cost ledger, SLO
    tallies, flight ring, time-series ring) — test isolation hook."""
    reset_metrics()
    _events.clear()
    spans.clear()
    costs.reset()
    slo.reset()
    flight.clear()
    timeseries.clear()


def __getattr__(name):
    # TelemetryCallback subclasses hapi.Callback; resolving it lazily keeps
    # this package importable from core.tensor before hapi exists.
    if name == 'TelemetryCallback':
        from .callback import TelemetryCallback
        return TelemetryCallback
    raise AttributeError(name)


# honor PADDLE_TPU_TELEMETRY=1 from the environment: state already read the
# flag; bring the jax hooks up with it
if enabled():
    install_jax_hooks()
