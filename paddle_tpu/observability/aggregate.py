"""Cross-rank telemetry aggregation: the supervisor side of mission control.

Reads the per-rank files a ``flush.RankFlusher`` writes into a run dir
(``telemetry_rank<R>.json`` / ``events_rank<R>.jsonl`` /
``trace_rank<R>.json`` plus the PR 5 supervisor's ``hb_<R>`` heartbeat
files) and merges them into:

- ``cluster_snapshot(run_dir)`` — one dict: per-rank step-time stats,
  compile/retrace counters, heartbeat ages, and cluster-wide counter
  totals. A straggling rank shows up as a skewed ``step_ms`` row, a
  retrace storm as one rank's ``jax_compiles`` still climbing.
- ``merged_events(run_dir)`` — every rank's JSONL events, rank-stamped and
  time-ordered: the stream the anomaly doctor diagnoses.
- ``merged_chrome_trace(run_dir)`` — a single Perfetto-loadable trace with
  ONE LANE PER RANK (rank = pid row, named ``rank <R> (host:pid)``), so a
  slow collective or straggling rank is visible as skewed lanes instead of
  a hang.
- ``merged_timeseries(run_dir)`` — per-series timelines merged from every
  rank's ``timeseries_rank<R>.json`` ring-sampler export (also embedded in
  the cluster snapshot under ``timeseries``): the trend evidence the
  doctor's ``page_leak`` / ``latency_creep`` / ``qps_collapse`` /
  ``compile_creep`` detectors read.
- ``write_merged(run_dir)`` — commits all three artifacts
  (``cluster_snapshot.json`` / ``merged_events.jsonl`` /
  ``merged_trace.json``) back into the run dir.

Deliberately standalone: stdlib-only and importable BY PATH (no package
imports) so ``tools/doctor.py`` / ``tools/telemetry_dump.py`` can aggregate
a run dir from a machine with no jax installed.
"""
import json
import os
import re
import time

__all__ = ['rank_files', 'load_rank_snapshots', 'heartbeat_ages',
           'cluster_snapshot', 'merged_events', 'merged_chrome_trace',
           'merged_timeseries', 'flight_dumps', 'write_merged']

_RANK_FILE_RE = re.compile(
    r'^(telemetry|events|trace|flight|timeseries)_rank(\d+)\.(json|jsonl)$')

#: histogram stats carried per time-series sample (mirrors timeseries.py)
_TS_HIST_KEYS = ('p50', 'p99', 'count')


def rank_files(run_dir):
    """``{rank: {'telemetry': path, 'events': path, 'trace': path}}`` for
    every per-rank telemetry file present in ``run_dir``."""
    out = {}
    try:
        names = os.listdir(run_dir)
    except OSError:
        return out
    for name in sorted(names):
        m = _RANK_FILE_RE.match(name)
        if not m:
            continue
        kind, rank = m.group(1), int(m.group(2))
        out.setdefault(rank, {})[kind] = os.path.join(run_dir, name)
    return out


def _load_json(path):
    try:
        with open(path, 'r', encoding='utf-8') as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def load_rank_snapshots(run_dir):
    """``{rank: head-dict}`` from each rank's ``telemetry_rank<R>.json``
    (rank/pid/host/ts/metrics/counters); unreadable files are skipped."""
    out = {}
    for rank, files in rank_files(run_dir).items():
        path = files.get('telemetry')
        if not path:
            continue
        head = _load_json(path)
        if isinstance(head, dict):
            out[rank] = head
    return out


def heartbeat_ages(run_dir, ranks=None):
    """Seconds since each rank's ``hb_<R>`` heartbeat file was touched
    (None = never written). Ranks default to every hb file present."""
    ages = {}
    if ranks is None:
        try:
            ranks = sorted(
                int(n[3:]) for n in os.listdir(run_dir)
                if n.startswith('hb_') and n[3:].isdigit())
        except OSError:
            ranks = []
    for rank in ranks:
        path = os.path.join(run_dir, f'hb_{rank}')
        try:
            ages[rank] = round(
                max(time.time() - os.path.getmtime(path), 0.0), 3)
        except OSError:
            ages[rank] = None
    return ages


def _hist(metrics, name):
    return (metrics or {}).get('histograms', {}).get(name) or {}


def cluster_snapshot(run_dir):
    """One cluster-wide dict merged from every rank's snapshot file.

    ``per_rank[rank]``: host/pid, flush ts, ``step_ms`` stats (hapi step
    histogram), step/compile/retrace/host-transfer tallies, dataloader
    wait sums, and heartbeat age. ``counters_total``: cluster sums of the
    interposed-counter summary. ``step_ms_skew``: max/median of per-rank
    mean step time — the straggler headline number."""
    heads = load_rank_snapshots(run_dir)
    ages = heartbeat_ages(run_dir, ranks=sorted(heads) or None)
    flights = flight_dumps(run_dir)
    per_rank, totals = {}, {}
    for rank, head in sorted(heads.items()):
        metrics = head.get('metrics') or {}
        counters = head.get('counters') or {}
        step = _hist(metrics, 'hapi.step_ms') or _hist(metrics, 'step_ms')
        per_rank[rank] = {
            'host': head.get('host'),
            'pid': head.get('pid'),
            'ts': head.get('ts'),
            'steps': step.get('count', 0),
            'step_ms': {k: step.get(k, 0.0)
                        for k in ('count', 'mean', 'p50', 'p99', 'max')},
            'jax_compiles': counters.get('jax_compiles', 0),
            'jax_traces': counters.get('jax_traces', 0),
            'host_transfer_bytes': counters.get('host_transfer_bytes', 0),
            'dataloader_wait_ms_sum': round(
                _hist(metrics, 'dataloader.next_wait_ms').get('sum', 0.0),
                3),
            'heartbeat_age_s': ages.get(rank),
        }
        for k, v in counters.items():
            if isinstance(v, (int, float)):
                totals[k] = round(totals.get(k, 0) + v, 3)
    means = [r['step_ms']['mean'] for r in per_rank.values()
             if r['step_ms'].get('count')]
    skew = 0.0
    if means:
        # lower median: with an even rank count the upper middle can BE the
        # straggler, flattening the very skew this number exists to show
        med = sorted(means)[(len(means) - 1) // 2]
        skew = round(max(means) / med, 3) if med > 0 else 0.0
    return {
        'run_dir': os.path.abspath(run_dir),
        'n_ranks': len(per_rank),
        'per_rank': per_rank,
        'counters_total': totals,
        'heartbeat_age_s': ages,
        'step_ms_skew': skew,
        # crash post-mortems: {rank: {reason, ts, exception?}} for every
        # flight_rank<R>.json a dying rank left behind — a rank may have a
        # dump and NO telemetry head (telemetry off, flight always-on)
        'flight_dumps': flights,
        # per-series timelines from the ring sampler (empty series dict
        # when no rank wrote a timeseries file — sampler off or old run)
        'timeseries': merged_timeseries(run_dir),
    }


def flight_dumps(run_dir):
    """``{rank: {'reason', 'ts', 'path', 'exception'?}}`` for every
    flight-recorder dump in the run dir (``tools/postmortem.py`` renders
    the full documents; the snapshot carries the headline)."""
    out = {}
    for rank, files in rank_files(run_dir).items():
        path = files.get('flight')
        if not path:
            continue
        doc = _load_json(path)
        if not isinstance(doc, dict) or 'reason' not in doc:
            continue
        row = {'reason': doc.get('reason'), 'ts': doc.get('ts'),
               'path': path}
        exc = doc.get('exception')
        if isinstance(exc, dict):
            row['exception'] = {'type': exc.get('type'),
                                'message': exc.get('message')}
        out[rank] = row
    return out


def merged_timeseries(run_dir):
    """Per-series timelines merged from every rank's
    ``timeseries_rank<R>.json`` (the ring sampler's delta-encoded export).

    Returns ``{'sample_every', 'per_rank': {rank: {'n_samples',
    'span_s'}}, 'series': {'counter:<name>'|'gauge:<name>'|
    'hist:<name>:<stat>': {rank: [[ts, value], ...]}}}`` — the shape the
    doctor's trend detectors and ``telemetry_dump --timeline`` consume.
    Counter timelines carry reconstructed cumulative totals
    (``counters_base + cumsum(deltas)``) and are dense: a sample with no
    delta still contributes its unchanged point, because a qps cliff IS
    the run of flat points. (Logic duplicated from ``timeseries.to_series``
    — this module stays standalone / importable by path.)"""
    series, per_rank, sample_every = {}, {}, None
    for rank, files in sorted(rank_files(run_dir).items()):
        path = files.get('timeseries')
        if not path:
            continue
        doc = _load_json(path)
        if not isinstance(doc, dict):
            continue
        samples = [s for s in (doc.get('samples') or [])
                   if isinstance(s, dict)]
        if sample_every is None and doc.get('sample_every'):
            sample_every = doc['sample_every']
        ts_list = [s.get('ts', 0) for s in samples]
        per_rank[rank] = {
            'n_samples': len(samples),
            'span_s': round(max(ts_list) - min(ts_list), 3)
            if len(ts_list) > 1 else 0.0,
        }
        cum = {k: v for k, v in (doc.get('counters_base') or {}).items()
               if isinstance(v, (int, float))}
        for s in samples:
            ts = s.get('ts', 0)
            for name, d in (s.get('counters') or {}).items():
                if isinstance(d, (int, float)):
                    cum[name] = cum.get(name, 0) + d
            for name, total in cum.items():
                series.setdefault(f'counter:{name}', {}) \
                    .setdefault(rank, []).append([ts, total])
            for name, v in (s.get('gauges') or {}).items():
                if isinstance(v, (int, float)):
                    series.setdefault(f'gauge:{name}', {}) \
                        .setdefault(rank, []).append([ts, v])
            for name, st in (s.get('histograms') or {}).items():
                if not isinstance(st, dict):
                    continue
                for k in _TS_HIST_KEYS:
                    v = st.get(k)
                    if isinstance(v, (int, float)):
                        series.setdefault(f'hist:{name}:{k}', {}) \
                            .setdefault(rank, []).append([ts, v])
    return {'sample_every': sample_every, 'per_rank': per_rank,
            'series': series}


def merged_events(run_dir):
    """Every rank's events, rank-stamped, ordered by wall timestamp."""
    out = []
    for rank, files in rank_files(run_dir).items():
        path = files.get('events')
        if not path:
            continue
        try:
            with open(path, 'r', encoding='utf-8') as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                rec.setdefault('rank', rank)
                out.append(rec)
    out.sort(key=lambda e: (e.get('ts', 0), e.get('rank', 0)))
    return out


def merged_chrome_trace(run_dir):
    """One Chrome trace-event list with a lane per rank.

    Each rank's span buffer uses its own pid/tid; remapping pid -> rank
    (plus ``process_name``/``process_sort_index`` metadata) gives Perfetto
    one named, ordered lane per rank, so cross-rank skew reads directly
    off the timeline."""
    heads = load_rank_snapshots(run_dir)
    out = []
    for rank, files in sorted(rank_files(run_dir).items()):
        path = files.get('trace')
        if not path:
            continue
        evs = _load_json(path)
        if not isinstance(evs, list):
            continue
        head = heads.get(rank) or {}
        label = f"rank {rank}"
        if head.get('host') or head.get('pid'):
            label += f" ({head.get('host', '?')}:{head.get('pid', '?')})"
        out.append({'name': 'process_name', 'ph': 'M', 'pid': rank,
                    'args': {'name': label}})
        out.append({'name': 'process_sort_index', 'ph': 'M', 'pid': rank,
                    'args': {'sort_index': rank}})
        for ev in evs:
            if isinstance(ev, dict):
                ev = dict(ev, pid=rank)
                out.append(ev)
    return out


def _commit(path, text):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, 'w', encoding='utf-8') as f:   # atomic-ok: staged,
        f.write(text)                             # committed by rename
    os.replace(tmp, path)


def write_merged(run_dir, out_dir=None):
    """Aggregate ``run_dir`` and commit the three merged artifacts into
    ``out_dir`` (default: the run dir itself). Returns
    ``{'snapshot': path, 'events': path, 'trace': path, 'n_ranks': n}``
    or None when the run dir has no per-rank telemetry files."""
    snap = cluster_snapshot(run_dir)
    if not snap['n_ranks']:
        return None
    out_dir = os.fspath(out_dir or run_dir)
    os.makedirs(out_dir, exist_ok=True)
    paths = {
        'snapshot': os.path.join(out_dir, 'cluster_snapshot.json'),
        'events': os.path.join(out_dir, 'merged_events.jsonl'),
        'trace': os.path.join(out_dir, 'merged_trace.json'),
    }
    _commit(paths['snapshot'], json.dumps(snap, sort_keys=True, indent=1))
    _commit(paths['events'], ''.join(
        json.dumps(e, sort_keys=True) + '\n' for e in merged_events(run_dir)))
    _commit(paths['trace'], json.dumps(merged_chrome_trace(run_dir)))
    paths['n_ranks'] = snap['n_ranks']
    return paths
