"""Cross-run performance baseline: the ``runs.jsonl`` registry + sentinel.

The in-run time series (``timeseries.py``) answers "what changed during
this run"; this module answers "what changed since last run". ``bench.py``
appends one summary record per round — BENCH extras, counter totals, the
cost-ledger headline, compile counts, and a config fingerprint — to an
append-only JSONL registry, and ``detect_regressions`` compares the latest
record against the rolling median of the prior runs, per metric:

- **robust**: rolling median + MAD (median absolute deviation), so one
  noisy historical run cannot drag the baseline; a metric must deviate by
  ``mad_k`` robust sigmas AND ``rel_threshold`` relative before it counts.
- **min-sample guard**: no verdicts until ``min_samples`` prior runs carry
  the metric — a two-run history proves nothing.
- **direction-aware**: qps/throughput DOWN is bad, latency/stall/compile
  UP is bad; metrics whose good direction is unknown stay quiet instead
  of guessing.

Registry record schema (one JSON object per line)::

    {'ts': 1722999999.5,            # epoch seconds (stamped if absent)
     'run': 'smoke',                # optional label
     'fingerprint': 'a3f9c2e1',     # config identity (same-config compare)
     'metrics': {'serving.latency_ms.p99': 12.5, 'train.qps': 3041, ...},
     'meta': {...}}                 # free-form, ignored by detection

Surfaced by ``tools/perfwatch.py`` (``compare`` / ``history`` /
``--fail-on regression`` CI gate) and the doctor's ``perf_regression``
detector. Stdlib-only and importable BY PATH (no hard package imports) so
the tools work with no jax installed; writes go through
``resilience.atomic_io`` when the package is importable, else the same
staged-rename spelling locally.
"""
import json
import os
import time

__all__ = ['default_runs_path', 'record_run', 'load_runs', 'flatten',
           'detect_regressions', 'compare', 'history', 'bad_direction']

try:                                    # package-relative when available;
    from ..resilience.atomic_io import atomic_write as _atomic_write
except ImportError:                     # path-loaded tools fall back below
    _atomic_write = None

#: metric-name markers whose GOOD direction is up (drop = regression) ...
_DOWN_BAD_MARKERS = ('qps', 'throughput', 'samples_per_sec',
                     'tokens_per_sec', 'goodput', 'bandwidth')
#: ... and whose BAD direction is up (growth = regression)
_UP_BAD_MARKERS = ('_ms', 'latency', 'p50', 'p99', 'stall', 'wait',
                   'compile', 'retrace', 'shed', 'expired', 'evict',
                   'preempt', 'restart', 'failure', 'error', 'cost',
                   'bytes')


def default_runs_path():
    """``PADDLE_TPU_RUNS_REGISTRY`` if set, else ``runs.jsonl`` under the
    telemetry dir (matching ``state.log_dir()`` without importing it)."""
    explicit = os.environ.get('PADDLE_TPU_RUNS_REGISTRY')
    if explicit:
        return explicit
    base = os.environ.get('PADDLE_TPU_TELEMETRY_DIR',
                          '/tmp/paddle_tpu_telemetry')
    return os.path.join(base, 'runs.jsonl')


def _commit(path, text):
    if _atomic_write is not None:
        os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
        _atomic_write(path, text.encode('utf-8'))
        return
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, 'w', encoding='utf-8') as f:   # atomic-ok: staged,
        f.write(text)                             # committed by rename
    os.replace(tmp, path)


def record_run(record, path=None):
    """Append one run record to the registry (whole-file rewrite committed
    by rename, so a concurrent reader never sees a torn line). Stamps
    ``ts`` when absent. Returns the registry path."""
    path = path or default_runs_path()
    record = dict(record)
    record.setdefault('ts', round(time.time(), 3))
    lines = []
    try:
        with open(path, 'r', encoding='utf-8') as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        pass
    lines.append(json.dumps(record, sort_keys=True, default=repr))
    _commit(path, '\n'.join(lines) + '\n')
    return path


def load_runs(path=None):
    """Every parseable record in the registry, file order (= append
    order: oldest first, latest last)."""
    path = path or default_runs_path()
    out = []
    try:
        with open(path, 'r', encoding='utf-8') as f:
            text = f.read()
    except OSError:
        return out
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            out.append(rec)
    return out


def flatten(record):
    """Numeric metrics of one record as a flat ``{dotted_name: value}``
    (nested dicts flatten with ``.`` joins; non-numeric leaves drop)."""
    out = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}.{k}" if prefix else str(k), v)
        elif isinstance(node, (int, float)) and not isinstance(node, bool):
            out[prefix] = node

    walk('', (record or {}).get('metrics') or {})
    return out


def bad_direction(metric):
    """``'down'`` when a drop regresses (qps-like), ``'up'`` when growth
    regresses (latency-like), None when unknown (stay quiet, don't
    guess)."""
    name = metric.lower()
    if any(m in name for m in _DOWN_BAD_MARKERS):
        return 'down'
    if any(m in name for m in _UP_BAD_MARKERS):
        return 'up'
    return None


def _median(vals):
    vals = sorted(vals)
    n = len(vals)
    mid = vals[n // 2]
    return (vals[n // 2 - 1] + mid) / 2 if n % 2 == 0 else mid


def detect_regressions(runs, min_samples=4, mad_k=4.0, rel_threshold=0.2,
                       same_fingerprint=True):
    """Latest run vs the rolling median+MAD of prior runs, per metric.

    Prior runs filter to the latest record's config fingerprint when it
    has one and enough matches exist (``same_fingerprint``) — comparing a
    new config against an old one measures the config change, not a
    regression; with too few same-config priors the full history is the
    baseline. Returns one dict per regressed metric::

        {'metric', 'value', 'median', 'mad', 'rel_change', 'direction',
         'bad_direction', 'n_baseline'}
    """
    if len(runs) < min_samples + 1:
        return []
    last, prior = runs[-1], runs[:-1]
    fp = last.get('fingerprint')
    if same_fingerprint and fp:
        matching = [r for r in prior if r.get('fingerprint') == fp]
        if len(matching) >= min_samples:
            prior = matching
    last_metrics = flatten(last)
    history_by_metric = {}
    for rec in prior:
        for name, v in flatten(rec).items():
            history_by_metric.setdefault(name, []).append(v)
    out = []
    for name, value in sorted(last_metrics.items()):
        bad = bad_direction(name)
        if bad is None:
            continue
        hist = history_by_metric.get(name) or []
        if len(hist) < min_samples:
            continue
        med = _median(hist)
        mad = _median([abs(v - med) for v in hist])
        # robust sigma with a relative floor: a perfectly flat history
        # (mad 0) must not turn measurement noise into a verdict
        scale = max(mad * 1.4826, abs(med) * 0.05, 1e-9)
        dev = (value - med) / scale
        rel = (value - med) / max(abs(med), 1e-9)
        direction = 'up' if value > med else 'down'
        if direction != bad:
            continue
        if abs(dev) < mad_k or abs(rel) < rel_threshold:
            continue
        out.append({'metric': name, 'value': value,
                    'median': round(med, 6), 'mad': round(mad, 6),
                    'rel_change': round(rel, 4), 'direction': direction,
                    'bad_direction': bad, 'n_baseline': len(hist)})
    out.sort(key=lambda r: -abs(r['rel_change']))
    return out


def compare(runs_or_path=None, **kw):
    """Convenience wrapper: latest-vs-history verdict for the CLI/doctor.
    Accepts a loaded run list or a registry path (None = default path)."""
    runs = (runs_or_path if isinstance(runs_or_path, list)
            else load_runs(runs_or_path))
    verdict = {'n_runs': len(runs), 'regressions': [],
               'last': runs[-1] if runs else None}
    if runs:
        verdict['regressions'] = detect_regressions(runs, **kw)
    return verdict


def history(runs, metric):
    """``[(ts, value), ...]`` for one metric across the registry."""
    out = []
    for rec in runs:
        v = flatten(rec).get(metric)
        if v is not None:
            out.append((rec.get('ts', 0), v))
    return out
