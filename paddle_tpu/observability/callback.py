"""TelemetryCallback: hapi.Model.fit instrumentation.

Wires the high-level training loop into the telemetry spine: a span per
step and per epoch, ``hapi.steps``/``hapi.step_ms``/``hapi.steps_per_sec``
metrics, a ``step`` event per batch (epoch, step, loss, step_ms) in the
step-event log, and — at train end — the JSONL event log plus the Chrome
trace written under ``log_dir`` and a ``train_end`` summary event carrying
the interposed counters (retraces, compiles, host-transfer bytes).

``Model.fit`` attaches one automatically while telemetry is enabled
(``PADDLE_TPU_TELEMETRY=1``), so a production run gets step events without
code changes; pass your own instance to control ``log_dir``.
"""
import os

from ..hapi.callbacks import Callback
from . import (doctor, endpoint, events, flight, flush, interpose, registry,
               spans, state, timeseries, timing)

__all__ = ['TelemetryCallback']


class TelemetryCallback(Callback):
    def __init__(self, log_dir=None, live_events=False):
        """``log_dir``: where ``events.jsonl`` / ``trace.json`` land at train
        end (default ``PADDLE_TPU_TELEMETRY_DIR``). ``live_events=True``
        additionally streams each event to ``events.jsonl`` as it is emitted
        (crash-tolerant, one extra host write per step)."""
        super().__init__()
        self.log_dir = log_dir
        self.live_events = live_events
        self._epoch = 0
        self._step_span = None
        self._epoch_timer = None
        self._train_sw = None
        self._steps_per_sec = None
        self._own_flusher = False
        self._own_sampler = False

    def _dir(self):
        return self.log_dir or state.log_dir()

    # -- train lifecycle ----------------------------------------------------
    def on_train_begin(self, logs=None):
        # the flight recorder's crash hooks ride along regardless of the
        # telemetry switch: a SIGTERM'd fit leaves its black box behind
        flight.install_crash_hooks()
        if not state.enabled():
            return
        self._train_sw = timing.Stopwatch()
        if self.live_events:
            d = self._dir()
            os.makedirs(d, exist_ok=True)
            events.set_sink(os.path.join(d, 'events.jsonl'))
        # mission control: inside a supervised cluster run, stream this
        # rank's telemetry to the run dir; with PADDLE_TPU_TELEMETRY_HTTP
        # set, export the live /metrics + /healthz endpoint for this fit
        had = flush.active_flusher() is not None
        self._own_flusher = (flush.start_rank_flusher() is not None
                             and not had)
        # the ring sampler runs for every telemetry-on fit (not just
        # supervised cluster runs): live /timeseries and the doctor's
        # trend detectors want timelines even single-process
        had_sampler = timeseries.active_sampler() is not None
        self._own_sampler = (timeseries.start_sampler() is not None
                             and not had_sampler)
        endpoint.maybe_start_from_env()
        events.emit('train_begin', epochs=self.params.get('epochs'),
                    steps=self.params.get('steps'))

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        if not state.enabled():
            return
        self._epoch_timer = timing.timer('hapi.epoch', epoch=epoch)
        self._epoch_timer.__enter__()
        events.emit('epoch_begin', epoch=epoch)

    def on_train_batch_begin(self, step, logs=None):
        if not state.enabled():
            return
        self._step_span = timing.timer('hapi.step', epoch=self._epoch,
                                       step=step)
        self._step_span.__enter__()

    def on_train_batch_end(self, step, logs=None):
        if self._step_span is None:
            return
        t = self._step_span
        self._step_span = None
        t.__exit__(None, None, None)
        if not state.enabled():
            return
        registry.counter('hapi.steps').inc()
        step_s = t.elapsed_ms / 1e3
        if step_s > 0:
            sps = 1.0 / step_s
            # EMA so the gauge reads steady-state throughput, not the last
            # batch's jitter
            self._steps_per_sec = sps if self._steps_per_sec is None else \
                0.9 * self._steps_per_sec + 0.1 * sps
            registry.gauge('hapi.steps_per_sec').set(
                round(self._steps_per_sec, 3))
        rec = {'epoch': self._epoch, 'step': step,
               'step_ms': round(t.elapsed_ms, 3)}
        loss = (logs or {}).get('loss')
        if isinstance(loss, (int, float)):
            rec['loss'] = float(loss)
        elif loss is not None:
            # engine DeviceLoss: record it only when the fit loop already
            # materialized it (log cadence) — the step event must never add
            # a host sync the steady-state pipeline would not have had
            ready = getattr(loss, 'is_ready', None)
            if ready is not None and ready():
                rec['loss'] = float(loss)
            elif ready is None:
                rec['loss'] = float(loss)
        events.emit('step', **rec)

    def on_epoch_end(self, epoch, logs=None):
        if self._epoch_timer is not None:
            self._epoch_timer.__exit__(None, None, None)
            self._epoch_timer = None
        if not state.enabled():
            return
        rec = {'epoch': epoch}
        for k, v in (logs or {}).items():
            if isinstance(v, (int, float)):
                rec[k] = float(v)
        events.emit('epoch_end', **rec)

    def on_eval_end(self, logs=None):
        if not state.enabled():
            return
        rec = {k: float(v) for k, v in (logs or {}).items()
               if isinstance(v, (int, float))}
        events.emit('eval_end', **rec)

    def on_train_end(self, logs=None):
        if self._step_span is not None:   # interrupted mid-batch
            self._step_span.__exit__(None, None, None)
            self._step_span = None
        if self._epoch_timer is not None:
            self._epoch_timer.__exit__(None, None, None)
            self._epoch_timer = None
        if not state.enabled():
            return
        jit_fn = getattr(self.model, '_jit_step_fn', None)
        if jit_fn is not None:
            try:
                registry.gauge('hapi.jit_cache_size').set(
                    jit_fn._cache_size())
            except Exception:
                pass
        events.emit('train_end',
                    total_s=round(self._train_sw.elapsed(), 3)
                    if self._train_sw else None,
                    counters=interpose.summary())
        # anomaly doctor over this run's own stream (retrace storms,
        # input-boundness): the findings land as `diagnosis` events so the
        # JSONL export below carries them
        try:
            doctor.run_doctor(events=events.events(),
                              snapshot=registry.snapshot(), emit=True)
        except Exception:
            pass   # diagnosis must never fail a training run
        # final per-rank flush so the aggregator sees the whole fit; the
        # flusher is only torn down when this fit started it (a spawn
        # worker's flusher outlives the fit — launch._worker owns it)
        sm = timeseries.active_sampler()
        if sm is not None:
            sm.sample_now()   # the run's tail lands in the ring
            if self._own_sampler:
                timeseries.stop_sampler()
                self._own_sampler = False
        fl = flush.active_flusher()
        if fl is not None:
            if self._own_flusher:
                flush.stop_rank_flusher()
                self._own_flusher = False
            else:
                fl.flush_now()
        if self.live_events:
            events.close_sink()
        d = self._dir()
        try:
            os.makedirs(d, exist_ok=True)
            events.dump_jsonl(os.path.join(d, 'events.jsonl'))
            spans.dump_chrome_trace(os.path.join(d, 'trace.json'))
        except OSError:
            pass   # telemetry export must never fail a training run
