"""Cost explorer: compiled-program cost attribution for the whole runtime.

Every program the runtime compiles — Executor program-cache entries, the
unified ``engine.build_train_step`` step, and the serving runners' closed
program sets — is captured ONCE at build/warmup time into a process-wide
**cost ledger** keyed by program label:

- ``flops`` / ``bytes_accessed`` from XLA's ``Compiled.cost_analysis()``;
- ``argument`` / ``output`` / ``temp`` / ``generated_code`` bytes (and
  their sum, ``peak_bytes``) from ``Compiled.memory_analysis()`` — all
  available on CPU, so the numbers are provable without a chip;
- an **analytic roofline** estimate: arithmetic intensity (flops per byte
  accessed) against configurable device peaks names whether the program is
  compute- or memory-bound and what its floor step time would be. The
  peaks are *nominal* (env-overridable), the estimate is a bound, not a
  measurement — see docs/OBSERVABILITY.md, "Cost explorer" for caveats.

Capture is an AOT ``fn.lower(*args).compile()`` — one extra backend
compile per program, paid once while the program is being built/warmed
anyway; repeat requests are ledger hits (``jax.compiles`` flatness gates
stay flat after warmup). Everything is off until telemetry is enabled.

Surfaces: ``cost.flops{program=}`` / ``cost.peak_bytes{program=}`` gauges,
one ``cost.program`` event per capture (what ``tools/telemetry_dump.py
--costs`` tabulates), the ``/costs`` endpoint slice, the per-rank flush
head, and BENCH ``extras.costs``.

Env knobs:

- ``PADDLE_TPU_DEVICE_PEAK_FLOPS``     roofline peak FLOP/s override
- ``PADDLE_TPU_DEVICE_PEAK_BPS``       roofline peak memory bytes/s override
- ``PADDLE_TPU_HBM_BUDGET``            device memory budget in bytes (the
                                       doctor's ``memory_pressure`` detector
                                       compares ledger ``peak_bytes`` to it)

Stdlib-only at import (jax is imported lazily inside ``capture``).
"""
import os
import threading

from . import events, registry, state

__all__ = ['capture', 'record_compiled', 'mark_hit', 'ledger', 'entry',
           'summary', 'reset', 'device_peaks', 'roofline', 'hbm_budget']

_lock = threading.Lock()
_ledger = {}         # program label -> entry dict


# nominal peak (FLOP/s, bytes/s) per backend for the analytic roofline —
# deliberately round numbers: the roofline is a *bound* used to rank
# programs and name the binding resource, not a performance prediction
_DEFAULT_PEAKS = {
    'tpu': (275e12, 1.2e12),     # ~v4 chip: bf16 MXU peak, HBM2 bw
    'gpu': (312e12, 2.0e12),     # ~A100 bf16 / HBM2e
    'cpu': (2e11, 5e10),         # a few AVX cores / dual-channel DRAM
}


def _env_float(name):
    raw = os.environ.get(name, '')
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def device_peaks(backend=None):
    """(peak_flops_per_s, peak_bytes_per_s) for the roofline: the env
    overrides when set, else the nominal table entry for the backend."""
    if backend is None:
        try:
            import jax
            backend = jax.default_backend()
        except Exception:
            backend = 'cpu'
    flops, bps = _DEFAULT_PEAKS.get(backend, _DEFAULT_PEAKS['cpu'])
    return (_env_float('PADDLE_TPU_DEVICE_PEAK_FLOPS') or flops,
            _env_float('PADDLE_TPU_DEVICE_PEAK_BPS') or bps)


def hbm_budget():
    """Device-memory budget in bytes for memory-pressure accounting:
    ``PADDLE_TPU_HBM_BUDGET`` when set, else the device's reported limit
    (TPU/GPU ``memory_stats``; CPU reports none), else None."""
    raw = os.environ.get('PADDLE_TPU_HBM_BUDGET', '')
    if raw:
        try:
            return int(float(raw))
        except ValueError:
            pass
    try:
        import jax
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get('bytes_limit')
        return int(limit) if limit else None
    except Exception:
        return None


def roofline(flops, bytes_accessed, backend=None):
    """Analytic roofline for one program: arithmetic intensity vs the
    device ridge point -> binding resource + floor time estimate."""
    peak_flops, peak_bps = device_peaks(backend)
    ai = (flops / bytes_accessed) if bytes_accessed else 0.0
    ridge = peak_flops / peak_bps
    est_s = max(flops / peak_flops if peak_flops else 0.0,
                bytes_accessed / peak_bps if peak_bps else 0.0)
    return {
        'arithmetic_intensity': round(ai, 4),
        'ridge': round(ridge, 4),
        'bound': 'compute' if ai >= ridge else 'memory',
        'est_ms': round(est_s * 1e3, 6),
        'peak_flops': peak_flops,
        'peak_bytes_per_s': peak_bps,
    }


def _cost_scalars(cost):
    """flops / bytes accessed from a ``cost_analysis()`` result (a dict in
    newer jax, a one-element list of dicts in older)."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return 0.0, 0.0
    return (float(cost.get('flops', 0.0) or 0.0),
            float(cost.get('bytes accessed', 0.0) or 0.0))


def _memory_scalars(mem):
    """argument/output/temp/generated-code bytes from ``memory_analysis()``
    (a CompiledMemoryStats-like object; absent fields read 0)."""
    def grab(attr):
        try:
            return int(getattr(mem, attr, 0) or 0)
        except (TypeError, ValueError):
            return 0
    return {
        'argument_bytes': grab('argument_size_in_bytes'),
        'output_bytes': grab('output_size_in_bytes'),
        'temp_bytes': grab('temp_size_in_bytes'),
        'alias_bytes': grab('alias_size_in_bytes'),
        'generated_code_bytes': grab('generated_code_size_in_bytes'),
    }


def capture(program, fn, *args, kind='jit', meta=None):
    """AOT-lower+compile ``fn`` at ``args``' shapes and ledger the result
    under ``program``. Returns the (possibly pre-existing) entry, or None
    when telemetry is off or the capture failed — a failed capture must
    never fail the program it describes. Idempotent per label: a second
    call is a ledger **hit** (no recompile), so cost numbers are stable
    across program-cache hits."""
    if not state.enabled():
        return None
    with _lock:
        ent = _ledger.get(program)
    if ent is not None:
        mark_hit(program)
        return ent
    try:
        compiled = fn.lower(*args).compile()
    except Exception as e:
        events.emit('cost.capture_error', program=str(program),
                    error=repr(e))
        return None
    return record_compiled(program, compiled, kind=kind, meta=meta)


def record_compiled(program, compiled, kind='jit', meta=None):
    """Ledger an already-compiled ``jax.stages.Compiled`` (the AOT-export
    path, or a capture that happened elsewhere)."""
    if not state.enabled():
        return None
    try:
        flops, bytes_accessed = _cost_scalars(compiled.cost_analysis())
    except Exception:
        flops = bytes_accessed = 0.0
    mem = {}
    try:
        mem = _memory_scalars(compiled.memory_analysis())
    except Exception:
        pass
    return record_costs(program, flops, bytes_accessed, mem,
                        kind=kind, meta=meta)


def record_costs(program, flops, bytes_accessed, mem=None, kind='jit',
                 meta=None):
    """Ledger raw numbers (the seam record_compiled/capture feed; also lets
    tests and external analyzers inject entries)."""
    if not state.enabled():
        return None
    mem = dict(mem or {})
    peak = (mem.get('argument_bytes', 0) + mem.get('output_bytes', 0) +
            mem.get('temp_bytes', 0) + mem.get('generated_code_bytes', 0))
    entry = {
        'program': str(program),
        'kind': str(kind),
        'flops': float(flops),
        'bytes_accessed': float(bytes_accessed),
        'peak_bytes': int(peak),
        'captured_ts': round(events.wall_ts(), 6),
        'hits': 0,
    }
    entry.update(mem)
    entry['roofline'] = roofline(entry['flops'], entry['bytes_accessed'])
    if meta:
        entry['meta'] = dict(meta)
    with _lock:
        fresh = program not in _ledger
        _ledger[program] = entry
    lbl = {'program': str(program)}
    registry.gauge('cost.flops', labels=lbl).set(entry['flops'])
    registry.gauge('cost.bytes_accessed', labels=lbl).set(
        entry['bytes_accessed'])
    registry.gauge('cost.peak_bytes', labels=lbl).set(entry['peak_bytes'])
    registry.counter('cost.captures').inc()
    if fresh:
        registry.counter('cost.programs').inc()
    events.emit('cost.program', program=entry['program'],
                program_kind=entry['kind'],
                flops=entry['flops'], bytes_accessed=entry['bytes_accessed'],
                peak_bytes=entry['peak_bytes'],
                argument_bytes=entry.get('argument_bytes', 0),
                output_bytes=entry.get('output_bytes', 0),
                temp_bytes=entry.get('temp_bytes', 0),
                arithmetic_intensity=entry['roofline'][
                    'arithmetic_intensity'],
                bound=entry['roofline']['bound'],
                est_ms=entry['roofline']['est_ms'])
    return entry


def mark_hit(program):
    """Count one reuse of a ledgered program (a program-cache hit)."""
    with _lock:
        ent = _ledger.get(program)
        if ent is not None:
            ent['hits'] += 1
    if state.enabled():
        registry.counter('cost.hits').inc()
    return ent


def entry(program):
    with _lock:
        ent = _ledger.get(program)
    return dict(ent) if ent is not None else None


def ledger():
    """Snapshot of every entry, sorted by descending flops."""
    with _lock:
        entries = [dict(e) for e in _ledger.values()]
    entries.sort(key=lambda e: (-e['flops'], e['program']))
    return entries


def summary():
    """Headline ledger aggregates (BENCH ``extras.costs``, flight dumps,
    the flush head)."""
    entries = ledger()
    peak_prog = max(entries, key=lambda e: e['peak_bytes'], default=None)
    by_kind = {}
    for e in entries:
        by_kind[e['kind']] = by_kind.get(e['kind'], 0) + 1
    out = {
        'programs': len(entries),
        'total_flops': round(sum(e['flops'] for e in entries), 1),
        'total_bytes_accessed': round(
            sum(e['bytes_accessed'] for e in entries), 1),
        'max_peak_bytes': peak_prog['peak_bytes'] if peak_prog else 0,
        'max_peak_program': peak_prog['program'] if peak_prog else None,
        'hits': sum(e['hits'] for e in entries),
        'by_kind': by_kind,
    }
    budget = hbm_budget()
    if budget:
        out['hbm_budget'] = budget
        out['peak_budget_ratio'] = round(
            out['max_peak_bytes'] / budget, 4)
    return out


def reset():
    with _lock:
        _ledger.clear()
