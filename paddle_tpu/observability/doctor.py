"""Anomaly doctor: streaming detectors over the telemetry spine.

Turns the raw counters/events mission control collects into a NAMED cause
and a fix-it hint. Each detector inspects the merged event stream and/or a
metrics snapshot (single-process or the aggregator's cluster snapshot) and
yields ``Diagnosis`` dicts::

    {'cause': 'straggler', 'severity': 'critical',
     'detail': 'rank 3 mean step 48.1ms vs cluster median 9.7ms (5.0x)',
     'fix': '...', 'evidence': {...}}

Detector catalog (docs/OBSERVABILITY.md has the operator version):

- ``straggler``       per-rank step-time skew in the cluster snapshot —
                      one rank's mean step time >= ``skew_threshold`` x
                      the cluster median (the ``faultinject.slow_rank``
                      failure mode; on hardware: a thermally throttled or
                      mis-scheduled chip).
- ``retrace_storm``   ``jax.compiles`` still growing after the warmup
                      steps (the dynamic-shape / unhashable-capture traps
                      graftlint GL005/GL006 + GL013 lint for statically).
- ``input_bound``     dataloader wait dominates step time — the
                      accelerator starves on host feed.
- ``serving_overload`` shed + deadline-expired requests trending up on the
                      serving event stream / counters — offered load
                      exceeds engine capacity. Page-exhaustion sheds are
                      EXCLUDED (that is memory pressure, not traffic —
                      see ``kv_page_exhaustion``).
- ``kv_page_exhaustion`` the paged KV cache ran out of pages: admission
                      blocked, decode rows stalled, sequences preempted,
                      or queue-full sheds attributed to page starvation.
                      The fix is memory-side (num_pages / page_size /
                      prefix_cache), never replicas or queue capacity.
- ``rank_flatline``   a rank's heartbeat is stale while siblings beat on
                      (wedged collective / dead process).
- ``memory_pressure`` the cost ledger's worst per-program ``peak_bytes``
                      approaches (>= 80%) or exceeds the device memory
                      budget (``PADDLE_TPU_HBM_BUDGET`` or the device's
                      reported limit) — the next bigger batch/sequence
                      OOMs. The fix is memory-side: microbatch, remat,
                      FSDP sharding.
- ``slo_burn``        a served model is burning its latency error budget
                      faster than its objective allows (the SLO tracker's
                      ``burn_rate``; warning at 1x, critical at 5x).
- ``checkpoint_stall`` synchronous checkpoint saves block the training
                      thread for >= 25% of the mean step time — the fix-it
                      is the async save path (``async_=True``), which
                      moves snapshot+commit off the step path.
- ``elastic_downsize`` the world size shrank mid-run: a rank died and the
                      elastic supervisor resumed on the survivors (info —
                      the run survived, but capacity is reduced; names
                      the dead rank from the supervisor's heartbeats).
- ``replica_flapping`` a serving replica's circuit breaker opened >=
                      ``flap_opens`` times this window — the half-open
                      gate keeps re-admitting a replica that is not
                      better (cold rejoin without warmup, flaky host);
                      the fix-it names the replica and the half-open
                      warmup knobs.
- ``retry_storm``     router failover retries >= 20% of offered load —
                      retry amplification melting the surviving
                      replicas; fix the failing replica, then bound
                      max_retries / hedging and let the shed ladder
                      engage first.
- ``noisy_neighbor``  one tenant owns >= ``noisy_share`` of the serving
                      pressure (quota/capacity sheds + SLO violations)
                      while other tenants share the same fleet — the
                      multi-tenant fairness failure per-tenant quotas
                      exist for. Reads the ``serving.tenant.*`` labeled
                      counters (snapshot) or tenant-stamped
                      ``serving.shed`` / ``serving.request`` events; the
                      fix-it names the tenant and its ``TenantPolicy``
                      rate/burst/weight knobs. Quiet with one tenant or a
                      healthy (shed-free) fleet.
- ``autoscale_flap``  the fleet autoscaler (or whatever is driving
                      replica count) reversed direction grow<->shrink
                      within a few cooldown windows, repeatedly — the
                      oscillation the hysteresis band + cooldown are
                      meant to make impossible; firing means a degenerate
                      band, cooldown 0, or two controllers fighting.
- ``cold_compile_storm`` a persistent compile cache is bound yet the boot
                      is compiling anyway: cached executables rejected at
                      load (CRC mismatch / jax version skew —
                      ``compilecache.incompat`` climbing), or the hit
                      rate collapsed against a populated dir (wrong dir /
                      stale program set). The fix-it names
                      ``tools/compilecache.py --verify`` and the
                      ``PADDLE_TPU_COMPILE_CACHE`` knob. Quiet when no
                      cache is bound or on the first populate pass.
- ``lint_debt``       the tree's justified graftlint waivers (inline
                      ``graftlint: disable`` + ``[[graftlint.waiver]]``
                      blocks) outgrew the ``lint_debt_threshold`` budget
                      recorded in graftlint.toml (info — the lint gate
                      still passes; this flags the creeping debt).

Trend detectors (need the ring sampler's timelines — ``timeseries`` in the
cluster snapshot, via ``aggregate.merged_timeseries``; quiet without them):

- ``page_leak``       KV page utilization grows monotonically while
                      occupancy (active slots) stays flat — pages are
                      allocated and never freed; a point snapshot shows
                      "high utilization", only the timeline shows it never
                      coming back down.
- ``latency_creep``   request p99 rises steadily over the run (last third
                      vs first third) — degradation too slow for any
                      single snapshot to flag.
- ``qps_collapse``    throughput cliff: the trailing window's per-sample
                      request rate collapsed vs the run median. The dense
                      counter timelines make the cliff visible — a stall
                      IS the run of flat cumulative points.
- ``compile_creep``   ``jax.compiles`` starts growing again after the
                      warmup plateau — the time-resolved upgrade of
                      ``retrace_storm`` (which needs compiles/steps to
                      already look bad in aggregate; this fires on the
                      inflection).
- ``perf_regression`` the latest run in the cross-run registry
                      (``runs.jsonl``, see ``baseline.py`` /
                      ``tools/perfwatch.py``) regressed vs the rolling
                      median + MAD of prior runs, direction-aware (qps
                      down = bad, latency/stall up = bad).

Ranked output: ``critical`` > ``warning`` > ``info``. Standalone on
purpose — stdlib-only, importable by path — so ``tools/doctor.py`` works
with no jax installed. When imported as part of the package,
``run_doctor(..., emit=True)`` also lands each diagnosis as a structured
``diagnosis`` event on the step-event log.
"""

__all__ = ['diagnose', 'run_doctor', 'render_report', 'DETECTORS',
           'SEVERITY_ORDER']

SEVERITY_ORDER = {'critical': 0, 'warning': 1, 'info': 2}

# tunables (detectors take overrides via **cfg)
SKEW_THRESHOLD = 1.75          # rank mean step vs cluster median
WARMUP_STEPS = 5               # compiles inside warmup are expected
RETRACE_GRACE = 3              # compiles beyond warmup that are tolerated
INPUT_BOUND_RATIO = 0.5        # dataloader wait / step time
OVERLOAD_RATIO = 0.05          # (shed + expired) / offered
STALE_HEARTBEAT_S = 10.0
MEMORY_PRESSURE_RATIO = 0.8    # worst program peak_bytes / memory budget
SLO_BURN_WARNING = 1.0         # error-budget burn rate thresholds
SLO_BURN_CRITICAL = 5.0
CHECKPOINT_STALL_RATIO = 0.25  # mean save stall / mean step time
FLAP_OPENS = 4                 # circuit opens per window = flapping
RETRY_STORM_RATIO = 0.2        # router retries / offered requests
RETRY_STORM_MIN = 10           # offered requests before the ratio counts
# trend-detector tunables (need the ring sampler's timelines)
PAGE_LEAK_MIN_SAMPLES = 5      # utilization points before a leak can fire
PAGE_LEAK_GROWTH = 0.1         # absolute utilization growth start -> end
PAGE_LEAK_OCCUPANCY_RANGE = 0.25   # active-slots rel. range still "stable"
PAGE_LEAK_CRITICAL_UTIL = 0.9  # last utilization point => critical
LATENCY_CREEP_MIN_SAMPLES = 6
LATENCY_CREEP_RATIO = 1.5      # last-third mean p99 / first-third mean
QPS_COLLAPSE_MIN_SAMPLES = 6
QPS_COLLAPSE_RATIO = 0.3       # trailing-window rate / run median rate
QPS_COLLAPSE_WINDOW = 3        # samples in the trailing window
COMPILE_CREEP_PLATEAU = 3      # consecutive zero-delta samples = warmed up
COMPILE_CREEP_GRACE = 3        # post-plateau compiles tolerated
COLD_STORM_COMPILES = 5        # boot compiles despite a populated cache
COLD_STORM_HIT_RATE = 0.5      # persistent-tier hit rate below = storm
COLD_STORM_INCOMPAT = 1        # rejected cache entries tolerated - 1
NOISY_SHARE = 0.6              # one tenant's share of sheds + violations
NOISY_MIN_PRESSURE = 5         # sheds + violations before a share counts
FLAP_REVERSALS = 2             # grow<->shrink direction flips = flapping
FLAP_WINDOW_COOLDOWNS = 3      # reversal counts within N cooldown spans


def _labeled(section, prefix, key='model'):
    """``{label_value: number}`` from snapshot keys shaped
    ``prefix{key=value}`` (the registry's labeled-instrument spelling).
    These families carry exactly ONE label key, so everything between
    ``key=`` and the closing brace IS the value — no comma split, which
    would truncate values that legitimately contain commas (the
    Executor's ``executor.p1[4x8,16x2]`` shape-signature labels)."""
    out = {}
    marker = prefix + '{' + key + '='
    for k, v in (section or {}).items():
        if k.startswith(marker) and k.endswith('}') and \
                isinstance(v, (int, float)):
            out[k[len(marker):-1]] = v
    return out


def _diag(cause, severity, detail, fix, **evidence):
    return {'cause': cause, 'severity': severity, 'detail': detail,
            'fix': fix, 'evidence': evidence}


def _hist(snapshot, name):
    return (snapshot or {}).get('histograms', {}).get(name) or {}


def _ctr(snapshot, name):
    return (snapshot or {}).get('counters', {}).get(name, 0)


# -- detectors --------------------------------------------------------------

def detect_straggler(events=None, snapshot=None, cluster=None,
                     skew_threshold=SKEW_THRESHOLD, **_):
    """Per-rank step-time skew from the cluster snapshot (>= 2 ranks with
    steps). Falls back to rank-stamped ``step`` events when no snapshot
    carries step histograms."""
    per_rank = {}
    if cluster:
        for rank, row in (cluster.get('per_rank') or {}).items():
            st = row.get('step_ms') or {}
            if st.get('count'):
                per_rank[int(rank)] = (float(st.get('mean', 0.0)),
                                       int(st['count']))
    if not per_rank and events:
        sums = {}
        for e in events:
            if e.get('ev') == 'step' and isinstance(
                    e.get('step_ms'), (int, float)) and 'rank' in e:
                s, n = sums.get(int(e['rank']), (0.0, 0))
                sums[int(e['rank'])] = (s + float(e['step_ms']), n + 1)
        per_rank = {r: (s / n, n) for r, (s, n) in sums.items() if n}
    if len(per_rank) < 2:
        return
    means = sorted(m for m, _n in per_rank.values())
    # lower median: with an even rank count the upper middle can BE the
    # straggler, hiding the skew
    median = means[(len(means) - 1) // 2]
    if median <= 0:
        return
    worst_rank, (worst_mean, worst_n) = max(
        per_rank.items(), key=lambda kv: kv[1][0])
    skew = worst_mean / median
    if skew < skew_threshold:
        return
    yield _diag(
        'straggler', 'critical',
        f"rank {worst_rank} mean step {worst_mean:.1f}ms vs cluster median "
        f"{median:.1f}ms ({skew:.1f}x) over {worst_n} step(s)",
        "inspect that rank's lane in merged_trace.json: a slow host "
        "(input pipeline, checkpoint I/O) shows host-side spans stretching; "
        "a slow chip shows uniform step stretch — reschedule the rank or "
        "drop it via elastic restart",
        rank=worst_rank, mean_step_ms=round(worst_mean, 3),
        median_step_ms=round(median, 3), skew=round(skew, 3),
        per_rank_mean_step_ms={r: round(m, 3)
                               for r, (m, _n) in sorted(per_rank.items())})


def detect_retrace_storm(events=None, snapshot=None, cluster=None,
                         warmup_steps=WARMUP_STEPS,
                         retrace_grace=RETRACE_GRACE, **_):
    """Compile count growth after warmup: in steady state every step reuses
    the cached program, so compiles beyond the warmed-up set mean the shape
    or hash key keeps changing (GL005/GL006/GL013 territory)."""
    rows = []
    if cluster:
        for rank, row in (cluster.get('per_rank') or {}).items():
            rows.append((f"rank {rank}", int(row.get('steps') or 0),
                         int(row.get('jax_compiles') or 0)))
    elif snapshot is not None:
        steps = int(_ctr(snapshot, 'hapi.steps')
                    or _hist(snapshot, 'hapi.step_ms').get('count', 0))
        rows.append(('process', steps, int(_ctr(snapshot, 'jax.compiles'))))
    for who, steps, compiles in rows:
        if steps <= warmup_steps:
            continue
        excess = compiles - warmup_steps - retrace_grace
        if excess <= 0 or compiles < 0.5 * steps:
            continue
        yield _diag(
            'retrace_storm', 'critical',
            f"{who}: {compiles} XLA compile(s) over {steps} step(s) — "
            "steady state should compile ~once; something retraces every "
            "step",
            "a traced argument's shape/dtype/hash changes per call: run "
            "`python -m paddle_tpu.analysis` (GL005/GL006 retrace traps, "
            "GL013 unbucketed shapes) and pad dynamic batches with "
            "serving.bucketing",
            who=who, steps=steps, compiles=compiles)


def detect_input_bound(events=None, snapshot=None, cluster=None,
                       input_bound_ratio=INPUT_BOUND_RATIO, **_):
    """Dataloader wait dominating step time: the device idles on host
    feed. Uses histogram sums (wait vs step) per process/cluster, plus the
    streamed ``input_stall`` events as corroborating evidence."""
    rows = []
    if cluster:
        for rank, row in (cluster.get('per_rank') or {}).items():
            st = row.get('step_ms') or {}
            step_sum = float(st.get('mean', 0.0)) * int(st.get('count') or 0)
            rows.append((f"rank {rank}",
                         float(row.get('dataloader_wait_ms_sum') or 0.0),
                         step_sum))
    elif snapshot is not None:
        rows.append(('process',
                     float(_hist(snapshot,
                                 'dataloader.next_wait_ms').get('sum', 0.0)),
                     float(_hist(snapshot, 'hapi.step_ms').get('sum', 0.0))))
    stalls = sum(1 for e in (events or []) if e.get('ev') == 'input_stall')
    for who, wait_ms, step_ms in rows:
        if step_ms <= 0 or wait_ms <= 0:
            continue
        ratio = wait_ms / step_ms
        if ratio < input_bound_ratio:
            continue
        yield _diag(
            'input_bound', 'warning',
            f"{who}: dataloader wait {wait_ms:.0f}ms is "
            f"{100 * ratio:.0f}% of step time {step_ms:.0f}ms — the step "
            "starves on host feed",
            "raise DataLoader num_workers / prefetch depth, move decode or "
            "augmentation off the step path, or shard the input files "
            "wider; dataloader.queue_depth should sit near its capacity",
            who=who, wait_ms=round(wait_ms, 1), step_ms=round(step_ms, 1),
            ratio=round(ratio, 3), input_stall_events=stalls)


def detect_serving_overload(events=None, snapshot=None, cluster=None,
                            overload_ratio=OVERLOAD_RATIO, **_):
    """Load shedding / deadline expiry trending up on the serving stream:
    offered load exceeds what the engine drains."""
    counters = (cluster or {}).get('counters_total') if cluster else None
    if counters is None and snapshot is not None:
        counters = {
            'serving_requests': _ctr(snapshot, 'serving.requests'),
            'serving_shed': _ctr(snapshot, 'serving.shed'),
            'serving_shed_page_exhaustion': _ctr(
                snapshot, 'serving.shed.page_exhaustion'),
            'serving_deadline_expired': _ctr(snapshot,
                                             'serving.deadline_expired'),
        }
    # serving.requests counts every submission (sheds included), so it IS
    # the offered load; the event stream reconstructs the same totals when
    # no counter snapshot is available. Page-exhaustion sheds are memory
    # pressure wearing a queue-full mask — kv_page_exhaustion owns those,
    # and counting them here would prescribe replicas for an OOM.
    offered = shed = expired = page_shed = 0
    if counters:
        offered = int(counters.get('serving_requests') or 0)
        shed = int(counters.get('serving_shed') or 0)
        page_shed = int(counters.get('serving_shed_page_exhaustion') or 0)
        expired = int(counters.get('serving_deadline_expired') or 0)
    if events:
        ev_shed = sum(1 for e in events if e.get('ev') == 'serving.shed')
        ev_pshed = sum(1 for e in events if e.get('ev') == 'serving.shed'
                       and e.get('reason') == 'page_exhaustion')
        ev_exp = sum(1 for e in events if e.get('ev') == 'serving.request'
                     and e.get('status') == 'deadline')
        ev_req = sum(1 for e in events if e.get('ev') == 'serving.request')
        shed = max(shed, ev_shed)
        page_shed = max(page_shed, ev_pshed)
        expired = max(expired, ev_exp)
        offered = max(offered, ev_req + ev_shed)
    shed = max(0, shed - page_shed)
    bad = shed + expired
    if not offered or not bad:
        return
    ratio = bad / offered
    if ratio < overload_ratio:
        return
    yield _diag(
        'serving_overload', 'warning' if ratio < 0.25 else 'critical',
        f"{bad} of {offered} request(s) shed or deadline-expired "
        f"({100 * ratio:.0f}%) — offered load exceeds engine capacity",
        "add engine replicas or raise queue_capacity only with more "
        "compute behind it; widen the bucket set so batches fill, or "
        "lower client deadlines so doomed work is shed at admission "
        "instead of after queueing",
        offered=offered, shed=shed, deadline_expired=expired,
        ratio=round(ratio, 3))


def detect_kv_page_exhaustion(events=None, snapshot=None, cluster=None, **_):
    """The paged KV cache ran out of pages: admission blocked behind page
    starvation (sheds attributed ``page_exhaustion``), decode rows
    stalled, or sequences were preempted to free memory. Distinct from
    ``serving_overload`` on purpose — the fix is pages, not replicas."""
    counters = (cluster or {}).get('counters_total') if cluster else None
    if counters is None and snapshot is not None:
        counters = {
            'serving_shed_page_exhaustion': _ctr(
                snapshot, 'serving.shed.page_exhaustion'),
            'serving_kv_decode_stalls': _ctr(snapshot,
                                             'serving.kv.decode_stalls'),
            'serving_kv_prefill_stalls': _ctr(snapshot,
                                              'serving.kv.prefill_stalls'),
            'serving_preemptions': _ctr(snapshot, 'serving.preemptions'),
        }
    page_shed = stalls = preempts = 0
    if counters:
        page_shed = int(counters.get('serving_shed_page_exhaustion') or 0)
        stalls = (int(counters.get('serving_kv_decode_stalls') or 0) +
                  int(counters.get('serving_kv_prefill_stalls') or 0))
        preempts = int(counters.get('serving_preemptions') or 0)
    if events:
        page_shed = max(page_shed, sum(
            1 for e in events if e.get('ev') == 'serving.shed'
            and e.get('reason') == 'page_exhaustion'))
        stalls = max(stalls, sum(
            1 for e in events if e.get('ev') == 'serving.page_exhausted'))
        preempts = max(preempts, sum(
            1 for e in events if e.get('ev') == 'serving.preempt'))
    if not (page_shed or stalls or preempts):
        return
    util = None
    if snapshot is not None:
        util = (snapshot.get('gauges') or {}).get(
            'serving.kv.page_utilization')
    severity = 'critical' if (page_shed or preempts) else 'warning'
    yield _diag(
        'kv_page_exhaustion', severity,
        f"paged KV cache out of pages: {page_shed} shed(s) attributed to "
        f"page exhaustion, {stalls} stall(s), {preempts} preemption(s)"
        + (f" at {100 * util:.0f}% page utilization"
           if isinstance(util, (int, float)) else ""),
        "grow num_pages (or shrink page_size to cut tail waste), enable "
        "prefix_cache= for shared system prompts, or lower "
        "max_new_tokens/deadlines; raising queue_capacity or adding "
        "replicas will NOT help — memory, not traffic, is the limit",
        page_exhaustion_sheds=page_shed, stalls=stalls,
        preemptions=preempts,
        **({'page_utilization': round(util, 4)}
           if isinstance(util, (int, float)) else {}))


def detect_rank_flatline(events=None, snapshot=None, cluster=None,
                         stale_heartbeat_s=STALE_HEARTBEAT_S, **_):
    """A rank whose heartbeat went stale while siblings stay fresh: a
    wedged collective or a dead process the deadline layer hasn't named
    yet."""
    ages = (cluster or {}).get('heartbeat_age_s') or {}
    fresh = [r for r, a in ages.items()
             if a is not None and a < stale_heartbeat_s]
    for rank, age in sorted(ages.items()):
        if age is None or age < stale_heartbeat_s or not fresh:
            continue
        yield _diag(
            'rank_flatline', 'critical',
            f"rank {rank} heartbeat is {age:.1f}s stale while "
            f"{len(fresh)} sibling(s) beat on — wedged or dead rank",
            "the supervisor's fail-fast should fire shortly; if not, check "
            "distributed.set_timeout (collective deadline) and the rank's "
            "stderr log in the run dir",
            rank=rank, heartbeat_age_s=age, fresh_ranks=sorted(fresh))


def detect_memory_pressure(events=None, snapshot=None, cluster=None,
                           hbm_budget=None,
                           memory_pressure_ratio=MEMORY_PRESSURE_RATIO, **_):
    """Worst per-program peak memory vs. the device budget, from the cost
    ledger's ``cost.peak_bytes{program=}`` gauges (snapshot) or
    ``cost.program`` events. Budget: the ``hbm_budget`` override, the
    ``PADDLE_TPU_HBM_BUDGET`` env (bytes), or — when jax is importable,
    which it is not from the path-loaded tools — the device's reported
    ``bytes_limit``."""
    import os
    budget = hbm_budget
    if budget is None:
        raw = os.environ.get('PADDLE_TPU_HBM_BUDGET', '')
        if raw:
            try:
                budget = int(float(raw))
            except ValueError:
                budget = None
    if budget is None:
        try:
            import jax
            stats = jax.devices()[0].memory_stats() or {}
            budget = int(stats.get('bytes_limit') or 0) or None
        except Exception:
            budget = None
    if not budget:
        return
    peaks = {}
    if snapshot is not None:
        peaks.update(_labeled(snapshot.get('gauges'), 'cost.peak_bytes',
                              key='program'))
    for e in (events or []):
        if e.get('ev') == 'cost.program' and isinstance(
                e.get('peak_bytes'), (int, float)):
            name = str(e.get('program', '?'))
            peaks[name] = max(peaks.get(name, 0), float(e['peak_bytes']))
    if not peaks:
        return
    worst_prog, worst = max(peaks.items(), key=lambda kv: kv[1])
    ratio = worst / budget
    if ratio < memory_pressure_ratio:
        return
    yield _diag(
        'memory_pressure', 'critical' if ratio >= 1.0 else 'warning',
        f"program {worst_prog!r} peaks at {worst / 1e6:.1f} MB = "
        f"{100 * ratio:.0f}% of the {budget / 1e6:.1f} MB device budget"
        + (" — it does not fit" if ratio >= 1.0 else
           " — the next bigger batch/sequence will not fit"),
        "cut live memory: engine.build_train_step(microbatch=k) to shrink "
        "the per-dispatch batch, remat='dots'/'full' to trade FLOPs for "
        "activations, sharding= (FSDP) to split params/optimizer state "
        "across the mesh, or page the serving KV cache down; raise "
        "PADDLE_TPU_HBM_BUDGET only if the budget was set conservatively",
        program=worst_prog, peak_bytes=int(worst), budget_bytes=int(budget),
        ratio=round(ratio, 4))


def detect_slo_burn(events=None, snapshot=None, cluster=None,
                    slo_burn_warning=SLO_BURN_WARNING,
                    slo_burn_critical=SLO_BURN_CRITICAL, **_):
    """Error-budget burn per served model, from the SLO tracker's
    ``slo.burn_rate{model=}`` gauge (snapshot) or the ``slo.violation``
    event stream. The gauge WINS where both exist: it is updated on every
    request, while a violation event carries the burn at emission — stale
    the moment good requests follow — so events only fill models the
    snapshot does not cover (bare event-log runs, flight dumps). Counts
    likewise take the max of the two sources, never their sum."""
    burns = {}
    counts = {}
    if snapshot is not None:
        burns.update(_labeled(snapshot.get('gauges'), 'slo.burn_rate'))
        counts.update(_labeled(snapshot.get('counters'), 'slo.violations'))
    ev_burns, ev_counts = {}, {}
    for e in (events or []):
        if e.get('ev') == 'slo.violation' and isinstance(
                e.get('burn_rate'), (int, float)):
            model = str(e.get('model', '?'))
            ev_burns[model] = float(e['burn_rate'])  # stream: last wins
            ev_counts[model] = ev_counts.get(model, 0) + 1
    for model, b in ev_burns.items():
        burns.setdefault(model, b)
    for model, n in ev_counts.items():
        counts[model] = max(counts.get(model, 0), n)
    for model, burn in sorted(burns.items()):
        if burn < slo_burn_warning:
            continue
        severity = 'critical' if burn >= slo_burn_critical else 'warning'
        yield _diag(
            'slo_burn', severity,
            f"model {model!r} is burning its latency error budget at "
            f"{burn:.1f}x the sustainable rate"
            + (f" ({int(counts[model])} violation(s))"
               if counts.get(model) else ""),
            "cut tail latency (widen buckets so batches fill, shrink "
            "max_new_tokens/deadlines, add prefix caching) or add "
            "capacity; if the objective is wrong, re-register with a "
            "realistic slo_ms — burning quietly hides real regressions",
            model=model, burn_rate=round(burn, 3),
            violations=int(counts.get(model, 0)))


def detect_checkpoint_stall(events=None, snapshot=None, cluster=None,
                            checkpoint_stall_ratio=CHECKPOINT_STALL_RATIO,
                            **_):
    """Checkpoint saves stalling the training thread: the mean
    ``checkpoint.save_stall_ms`` (training-thread blocked time — the full
    commit for synchronous saves, ~0 for async ones) is a large fraction
    of the mean step time. The fix is the async save path, not a faster
    disk."""
    stall_mean = stall_count = step_mean = 0.0
    if snapshot is not None:
        h = _hist(snapshot, 'checkpoint.save_stall_ms')
        stall_mean, stall_count = float(h.get('mean', 0.0)), \
            int(h.get('count') or 0)
        for name in ('hapi.step_ms', 'engine.step_ms'):
            sh = _hist(snapshot, name)
            if sh.get('count'):
                step_mean = float(sh.get('mean', 0.0))
                break
    if (not stall_count or not step_mean) and events:
        # event-stream fallback: synchronous saves' commit time IS their
        # stall; async saves are excluded (their stall is the enqueue)
        durs = [float(e['duration_ms']) for e in events
                if e.get('ev') == 'checkpoint.save'
                and not e.get('async_')
                and isinstance(e.get('duration_ms'), (int, float))]
        steps = [float(e['step_ms']) for e in events
                 if e.get('ev') == 'step'
                 and isinstance(e.get('step_ms'), (int, float))]
        if durs and steps:
            stall_mean = sum(durs) / len(durs)
            stall_count = len(durs)
            step_mean = sum(steps) / len(steps)
    if not stall_count or step_mean <= 0 or stall_mean <= 0:
        return
    ratio = stall_mean / step_mean
    if ratio < checkpoint_stall_ratio:
        return
    yield _diag(
        'checkpoint_stall', 'warning',
        f"checkpoint saves stall the training thread {stall_mean:.1f}ms "
        f"on average = {100 * ratio:.0f}% of the {step_mean:.1f}ms mean "
        f"step, over {stall_count} save(s)",
        "use the async save path: CheckpointManager.save(async_=True), "
        "CheckpointSaver(async_save=True), or engine.fit(checkpoint=..., "
        "async_save=True) — the snapshot+commit move to a background "
        "thread and checkpoint.save_stall_ms drops to ~0 "
        "(checkpoint.commit_ms keeps the true disk latency)",
        stall_ms=round(stall_mean, 3), step_ms=round(step_mean, 3),
        ratio=round(ratio, 3), saves=stall_count)


def detect_elastic_downsize(events=None, snapshot=None, cluster=None, **_):
    """The world size shrank mid-run: a rank died and the elastic
    supervisor re-formed the mesh with the survivors instead of
    fail-fasting. Informational by design — the run SURVIVED — but every
    downsize means less throughput and one less failure the budget can
    absorb, so it must never pass silently."""
    downs = [e for e in (events or [])
             if e.get('ev') == 'elastic.downsize']
    count = len(downs)
    for src in (snapshot, None if cluster is None else
                {'counters': cluster.get('counters_total') or {}}):
        if src is not None:
            count = max(count, int(_ctr(
                src, 'distributed.elastic_downsizes') or 0))
    if not count:
        return
    recov = _hist(snapshot, 'elastic.recovery_ms') if snapshot else {}
    for e in downs or [{}]:
        dead = e.get('dead_rank')
        detail = (f"world shrank {e.get('old_world', '?')} -> "
                  f"{e.get('new_world', '?')}"
                  + (f" after rank {dead} died"
                     + (f" ({e['signal']})" if e.get('signal') else "")
                     if dead is not None else "")) if e else \
            f"{count} elastic downsize(s) this run"
        yield _diag(
            'elastic_downsize', 'info', detail,
            "the job survived on fewer ranks; restore full capacity by "
            "bringing a replacement up inside the rejoin grace window "
            "(rejoin_<rank> marker / a rescheduled node), or expect "
            "proportionally lower throughput until the next full restart",
            downsizes=count,
            **({'dead_rank': dead} if e and dead is not None else {}),
            **({'recovery_ms_p50': round(recov['p50'], 1)}
               if recov.get('count') else {}))
        if not e:
            break


def detect_replica_flapping(events=None, snapshot=None, cluster=None,
                            flap_opens=FLAP_OPENS, **_):
    """A serving replica's circuit breaker is oscillating: it opened
    ``flap_opens``+ times this window (``serving.router.circuit``
    events), usually with closes in between — the half-open probe window
    keeps re-admitting a replica that is not actually better (cold
    compile storm on rejoin, flaky host, undersized warmup), so live
    traffic keeps paying the failure tax."""
    opens, closes, last_reason = {}, {}, {}
    for e in (events or []):
        if e.get('ev') != 'serving.router.circuit':
            continue
        rep = str(e.get('replica', '?'))
        if e.get('state') == 'open':
            opens[rep] = opens.get(rep, 0) + 1
            if e.get('reason'):
                last_reason[rep] = str(e['reason'])
        elif e.get('state') == 'closed':
            closes[rep] = closes.get(rep, 0) + 1
    if not opens:
        # last-wins router_stats fallback (flight dumps with a short
        # event window): lifetime trip counts, no close attribution
        for e in reversed(events or []):
            if e.get('ev') == 'serving.router_stats':
                for rep, row in (e.get('replicas') or {}).items():
                    if isinstance(row, dict) and row.get('trips'):
                        opens[str(rep)] = int(row['trips'])
                break
    for rep, n in sorted(opens.items()):
        if n < flap_opens:
            continue
        severity = 'critical' if n >= 2 * flap_opens else 'warning'
        yield _diag(
            'replica_flapping', severity,
            f"replica {rep!r} circuit opened {n} time(s)"
            + (f", closed {closes[rep]} time(s)" if closes.get(rep) else "")
            + (f" (last trip: {last_reason[rep]})"
               if last_reason.get(rep) else "")
            + " — it keeps being re-admitted and keeps failing",
            f"stop the flap at replica {rep!r}: lengthen its half-open "
            "warmup (raise RouterPolicy.half_open_probes and "
            "circuit_cooldown_s so a rejoining replica proves itself on "
            "more probes before taking real traffic), make sure the "
            "relaunch path calls warmup() so probes don't hit a cold "
            "compile storm, and if it still trips, drain() it and "
            "inspect the host instead of letting the breaker babysit it",
            replica=rep, opens=n, closes=int(closes.get(rep, 0)),
            **({'last_trip': last_reason[rep]}
               if last_reason.get(rep) else {}))


def detect_retry_storm(events=None, snapshot=None, cluster=None,
                       retry_storm_ratio=RETRY_STORM_RATIO,
                       retry_storm_min=RETRY_STORM_MIN, **_):
    """Router failover retries are a large fraction of offered load —
    retry amplification: every failed request multiplies into several
    dispatched ones, which is exactly how a degraded fleet melts the
    healthy replicas too. Offered = first-attempt dispatches (dispatched
    minus retries minus hedges); fires at ``retries/offered >=``
    ``retry_storm_ratio`` once at least ``retry_storm_min`` requests were
    offered."""
    dispatched = retries = hedges = 0
    if snapshot is not None:
        # per-replica labeled families (one label set per family): the
        # fleet total is the sum over replica labels
        ctrs = snapshot.get('counters')
        dispatched = int(sum(_labeled(
            ctrs, 'serving.router.dispatched', key='replica').values()))
        retries = int(sum(_labeled(
            ctrs, 'serving.router.retries', key='replica').values()))
        hedges = int(sum(_labeled(
            ctrs, 'serving.router.hedges', key='replica').values()))
    if not dispatched:
        for e in reversed(events or []):   # last-wins cumulative event
            if e.get('ev') == 'serving.router_stats':
                for row in (e.get('replicas') or {}).values():
                    if isinstance(row, dict):
                        dispatched += int(row.get('dispatched') or 0)
                        retries += int(row.get('retried') or 0)
                        hedges += int(row.get('hedged') or 0)
                break
    offered = dispatched - retries - hedges
    if offered < retry_storm_min or retries <= 0:
        return
    ratio = retries / offered
    if ratio < retry_storm_ratio:
        return
    severity = 'critical' if ratio >= 2 * retry_storm_ratio else 'warning'
    yield _diag(
        'retry_storm', severity,
        f"{retries} failover retries on {offered} offered request(s) = "
        f"{100 * ratio:.0f}% amplification — the fleet is re-dispatching "
        "a large share of its load onto the surviving replicas",
        "find WHY requests fail over (serving.router.failover events and "
        "the circuit log name the replica) and fix that replica; then "
        "bound the blast radius — lower RouterPolicy.max_retries, keep "
        "hedging for tail latency only (hedge_after_ms near p95, not "
        "p50), and check the shed ladder thresholds engage before "
        "retries do, so overload sheds instead of amplifying",
        dispatched=dispatched, retries=retries, hedges=hedges,
        offered=offered, ratio=round(ratio, 3))


def detect_lint_debt(events=None, snapshot=None, cluster=None,
                     lint_debt_threshold=None, repo_root=None, **_):
    """The repo's justified-waiver count outgrew the budget recorded in
    ``graftlint.toml`` (``lint_debt_threshold``). Every waiver is a rule
    firing that somebody argued around; past the budget the arguing is
    the norm and the linter has stopped steering. Info-only: the gate
    (tier-1 lint) still passes — this names the creeping debt before a
    waiver-heavy PR normalizes it. Quiet when no budget is recorded or
    the tree is not checked out (installed package without sources)."""
    import os
    import re
    root = repo_root
    if root is None:
        here = os.path.dirname(os.path.abspath(__file__))
        root = os.path.dirname(os.path.dirname(here))
    toml = os.path.join(root, 'graftlint.toml')
    if not os.path.isfile(toml):
        return
    try:
        with open(toml, 'r', encoding='utf-8') as f:
            cfg_text = f.read()
    except OSError:
        return
    if lint_debt_threshold is None:
        m = re.search(r'^\s*lint_debt_threshold\s*=\s*(\d+)', cfg_text,
                      re.MULTILINE)
        if m is None:
            return
        lint_debt_threshold = int(m.group(1))
    file_waivers = len(re.findall(r'\[\[graftlint\.waiver\]\]', cfg_text))
    inline = 0
    pkg = os.path.join(root, 'paddle_tpu')
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != '__pycache__']
        for fn in filenames:
            if not fn.endswith('.py'):
                continue
            try:
                with open(os.path.join(dirpath, fn), 'r',
                          encoding='utf-8') as f:
                    inline += len(re.findall(r'#\s*graftlint:\s*disable',
                                             f.read()))
            except OSError:
                continue
    total = file_waivers + inline
    if total <= int(lint_debt_threshold):
        return
    yield _diag(
        'lint_debt', 'info',
        f"{total} graftlint waiver(s) in the tree ({inline} inline, "
        f"{file_waivers} file-level) exceed the lint_debt_threshold="
        f"{lint_debt_threshold} budget recorded in graftlint.toml",
        "burn down the debt before adding to it: re-read the oldest "
        "waivers (git log -S 'graftlint: disable'), fix the ones whose "
        "justification no longer holds, and only then raise "
        "lint_debt_threshold for the remainder that is genuinely "
        "by-design",
        waivers=total, inline=inline, file_level=file_waivers,
        threshold=int(lint_debt_threshold))


# -- trend detectors (ring-sampler timelines) -------------------------------

def _series(snapshot=None, cluster=None):
    """Per-series timelines (``aggregate.merged_timeseries`` shape) from
    the cluster snapshot, falling back to any ``timeseries`` block on the
    plain snapshot. Empty dict when the run has no sampler output — every
    trend detector is quiet then."""
    for doc in (cluster, snapshot):
        ts = (doc or {}).get('timeseries')
        if isinstance(ts, dict) and isinstance(ts.get('series'), dict):
            return ts['series']
    return {}


def _timelines(entry):
    """``(rank, [(ts, value), ...])`` per rank from one series entry —
    ranks come back as strings after a JSON round trip, values must be
    numeric pairs."""
    for rank, tl in sorted((entry or {}).items(), key=lambda kv: str(kv[0])):
        try:
            rank = int(rank)
        except (TypeError, ValueError):
            pass
        pts = [(p[0], p[1]) for p in (tl or [])
               if isinstance(p, (list, tuple)) and len(p) == 2
               and isinstance(p[1], (int, float))]
        if pts:
            yield rank, pts


def detect_page_leak(events=None, snapshot=None, cluster=None,
                     page_leak_min_samples=PAGE_LEAK_MIN_SAMPLES,
                     page_leak_growth=PAGE_LEAK_GROWTH,
                     page_leak_occupancy_range=PAGE_LEAK_OCCUPANCY_RANGE,
                     **_):
    """KV page utilization climbing monotonically while occupancy stays
    flat: pages are allocated and never freed. A point snapshot only says
    "utilization is high" — the timeline shows it never comes back down
    even though the engine is serving the same number of sequences."""
    series = _series(snapshot, cluster)
    util = series.get('gauge:serving.kv.page_utilization') or {}
    slots = dict(_timelines(series.get('gauge:serving.active_slots') or {}))
    for rank, tl in _timelines(util):
        vals = [v for _ts, v in tl]
        if len(vals) < page_leak_min_samples:
            continue
        growth = vals[-1] - vals[0]
        if growth < page_leak_growth:
            continue
        # a leak never gives pages back: any real dip means churn, not leak
        if any(b < a - 1e-6 for a, b in zip(vals, vals[1:])):
            continue
        # stable occupancy separates a leak from genuine load growth
        occ = [v for _ts, v in slots.get(rank, [])]
        if occ:
            lo, hi = min(occ), max(occ)
            if hi > 0 and (hi - lo) / hi > page_leak_occupancy_range:
                continue
        severity = ('critical' if vals[-1] >= PAGE_LEAK_CRITICAL_UTIL
                    else 'warning')
        yield _diag(
            'page_leak', severity,
            f"rank {rank}: KV page utilization grew "
            f"{vals[0]:.2f} -> {vals[-1]:.2f} monotonically over "
            f"{len(vals)} sample(s) with stable occupancy — pages are "
            "allocated and never freed",
            "audit the page release paths: every PageAllocator.alloc() "
            "needs a matching decref() on sequence finish AND on "
            "preemption/cancel; utilization should fall whenever "
            "active_slots does. tools/telemetry_dump.py --timeline "
            "--series page_utilization shows the climb",
            rank=rank, first_util=round(vals[0], 4),
            last_util=round(vals[-1], 4), growth=round(growth, 4),
            n_samples=len(vals))


def detect_latency_creep(events=None, snapshot=None, cluster=None,
                         latency_creep_min_samples=LATENCY_CREEP_MIN_SAMPLES,
                         latency_creep_ratio=LATENCY_CREEP_RATIO,
                         latency_series='hist:serving.latency_ms:p99', **_):
    """Request p99 rising steadily over the run: last-third mean vs
    first-third mean, and the timeline mostly rising — degradation too
    slow for any single snapshot (or the SLO burn-rate window) to flag."""
    series = _series(snapshot, cluster)
    for rank, tl in _timelines(series.get(latency_series) or {}):
        vals = [v for _ts, v in tl]
        if len(vals) < latency_creep_min_samples:
            continue
        third = max(len(vals) // 3, 1)
        head = sum(vals[:third]) / third
        tail = sum(vals[-third:]) / third
        if head <= 0 or tail < latency_creep_ratio * head:
            continue
        rising = sum(1 for a, b in zip(vals, vals[1:]) if b >= a - 1e-9)
        if rising < 0.6 * (len(vals) - 1):
            continue
        ratio = tail / head
        severity = ('critical' if ratio >= 2 * latency_creep_ratio
                    else 'warning')
        yield _diag(
            'latency_creep', severity,
            f"rank {rank}: {latency_series.split(':', 1)[1]} crept "
            f"{head:.1f} -> {tail:.1f} ({ratio:.1f}x) over "
            f"{len(vals)} sample(s)",
            "slow accumulation, not a spike: look for resource growth in "
            "the same window (page_leak, queue_depth, compile_creep) — "
            "tools/telemetry_dump.py --timeline lines the series up; if "
            "nothing grows, suspect host-side interference on that rank",
            rank=rank, first_third_mean=round(head, 3),
            last_third_mean=round(tail, 3), ratio=round(ratio, 3),
            n_samples=len(vals), series=latency_series)


def detect_qps_collapse(events=None, snapshot=None, cluster=None,
                        qps_collapse_min_samples=QPS_COLLAPSE_MIN_SAMPLES,
                        qps_collapse_ratio=QPS_COLLAPSE_RATIO,
                        qps_collapse_window=QPS_COLLAPSE_WINDOW, **_):
    """Throughput cliff: the trailing window's per-sample request rate
    collapsed vs the run median. The cumulative counter timelines are
    dense (a sample with no delta still contributes a flat point), so a
    stall shows up as exactly this — flat tail, healthy median."""
    series = _series(snapshot, cluster)
    entry = None
    for name in ('counter:serving.requests', 'counter:hapi.steps'):
        entry = series.get(name)
        if entry:
            break
    if not entry:
        return
    for rank, tl in _timelines(entry):
        if len(tl) < qps_collapse_min_samples:
            continue
        deltas = [b[1] - a[1] for a, b in zip(tl, tl[1:])]
        busy = sorted(d for d in deltas if d > 0)
        if len(busy) < qps_collapse_window:
            continue
        run_med = busy[len(busy) // 2]
        tail = sorted(deltas[-qps_collapse_window:])
        tail_med = tail[len(tail) // 2]
        if run_med <= 0 or tail_med > qps_collapse_ratio * run_med:
            continue
        yield _diag(
            'qps_collapse', 'critical',
            f"rank {rank}: {name.split(':', 1)[1]} rate collapsed to "
            f"{tail_med:.1f}/sample in the last {qps_collapse_window} "
            f"sample(s) vs run median {run_med:.1f}/sample",
            "the engine is alive (samples keep landing) but work stopped "
            "flowing: check admission (queue_depth / shed counters), the "
            "paged-KV pool (kv_page_exhaustion / page_leak), and upstream "
            "feed; merged_trace.json shows which stage went quiet",
            rank=rank, tail_rate=round(tail_med, 3),
            median_rate=round(run_med, 3),
            ratio=round(tail_med / run_med, 3), series=name,
            n_samples=len(tl))


def detect_compile_creep(events=None, snapshot=None, cluster=None,
                         compile_creep_plateau=COMPILE_CREEP_PLATEAU,
                         compile_creep_grace=COMPILE_CREEP_GRACE, **_):
    """``jax.compiles`` growing again AFTER the warmup plateau — the
    time-resolved upgrade of ``retrace_storm``: that one needs the
    aggregate compiles/steps ratio to already look bad; this fires on the
    inflection, while the cumulative total still looks innocent."""
    series = _series(snapshot, cluster)
    for rank, tl in _timelines(series.get('counter:jax.compiles') or {}):
        vals = [v for _ts, v in tl]
        if len(vals) < compile_creep_plateau + 2:
            continue
        # the warmup plateau: the first run of >= plateau consecutive
        # zero-delta samples (steady state reuses the cached program)
        plateau_end, flat = None, 0
        for i in range(1, len(vals)):
            if vals[i] == vals[i - 1]:
                flat += 1
                if flat >= compile_creep_plateau and plateau_end is None:
                    plateau_end = i
            else:
                flat = 0
        if plateau_end is None:
            continue
        post = vals[-1] - vals[plateau_end]
        if post < compile_creep_grace:
            continue
        yield _diag(
            'compile_creep', 'warning',
            f"rank {rank}: {post:.0f} new XLA compile(s) after the warmup "
            f"plateau ({vals[plateau_end]:.0f} compiles held flat for "
            f"{compile_creep_plateau}+ samples, now {vals[-1]:.0f})",
            "something started retracing mid-run: a shape or static "
            "argument changed after warmup (late dataset tail batch, "
            "config flip, eval path with new shapes) — diff the traced "
            "signatures around the inflection; graftlint GL005/GL006/"
            "GL013 name the static culprits",
            rank=rank, plateau_compiles=vals[plateau_end],
            final_compiles=vals[-1], post_plateau=post,
            n_samples=len(vals))


def _load_baseline():
    """The cross-run baseline module, package-relative or by path (this
    module is loaded standalone by tools/doctor.py)."""
    if __package__:
        from . import baseline
        return baseline
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'baseline.py')
    try:
        spec = importlib.util.spec_from_file_location(
            'paddle_tpu_baseline_standalone', path)
        if spec is None or spec.loader is None:
            return None
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
    except (OSError, ImportError):
        return None


def detect_perf_regression(events=None, snapshot=None, cluster=None,
                           runs_path=None, perf_min_samples=None, **_):
    """The latest run in the cross-run registry regressed vs the rolling
    median + MAD of prior runs (``baseline.detect_regressions`` — robust,
    direction-aware). Points at the registry via ``runs_path`` or
    ``PADDLE_TPU_RUNS_REGISTRY``; quiet without one."""
    import os
    path = runs_path or os.environ.get('PADDLE_TPU_RUNS_REGISTRY')
    if not path or not os.path.isfile(path):
        return
    baseline = _load_baseline()
    if baseline is None:
        return
    kw = {} if perf_min_samples is None else \
        {'min_samples': int(perf_min_samples)}
    runs = baseline.load_runs(path)
    for reg in baseline.detect_regressions(runs, **kw):
        severity = ('critical' if abs(reg.get('rel_change', 0)) >= 0.5
                    else 'warning')
        yield _diag(
            'perf_regression', severity,
            f"{reg['metric']}: last run {reg['value']:g} vs rolling median "
            f"{reg['median']:g} of {reg['n_baseline']} prior run(s) "
            f"({reg['direction']} {100 * abs(reg['rel_change']):.0f}%, "
            f"bad direction: {reg['bad_direction']})",
            "tools/perfwatch.py history --metric <name> shows the trend; "
            "bisect the runs between the last healthy record and this one "
            "(each record carries its config fingerprint) — if the change "
            "is intentional, land a new baseline by letting healthy runs "
            "accumulate past the window",
            metric=reg['metric'], value=reg['value'],
            median=reg['median'], mad=reg.get('mad', 0),
            rel_change=reg['rel_change'], direction=reg['direction'],
            n_baseline=reg['n_baseline'])


def detect_cold_compile_storm(events=None, snapshot=None, cluster=None,
                              cold_storm_compiles=COLD_STORM_COMPILES,
                              cold_storm_hit_rate=COLD_STORM_HIT_RATE,
                              cold_storm_incompat=COLD_STORM_INCOMPAT,
                              **_):
    """A persistent compile cache is bound and consulted, yet the process
    is paying the boot compile storm anyway — the zero-compile-boot
    contract is broken. Two firing shapes:

    - ``compilecache.incompat`` >= ``cold_storm_incompat``: entries are
      being REJECTED (CRC mismatch from torn/corrupted files, jax/backend
      version skew, topology drift) — every rejection is a paid compile
      that a healthy cache would have served (critical when rejections
      dominate the lookups: the cache is effectively poisoned).
    - hit rate below ``cold_storm_hit_rate`` while ``jax.compiles`` >=
      ``cold_storm_compiles``: lookups mostly miss, i.e. the dir the
      process was pointed at was populated by a different program set /
      key anatomy (wrong dir, changed labels, changed shapes).

    Quiet when no cache is bound (no ``compilecache.*`` lookups — a first
    boot against an EMPTY dir is also quiet: misses with near-zero prior
    inventory are the populate pass, not a storm)."""
    if snapshot is None:
        return
    hits = int(_ctr(snapshot, 'compilecache.hits'))
    misses = int(_ctr(snapshot, 'compilecache.misses'))
    incompat = int(_ctr(snapshot, 'compilecache.incompat'))
    lookups = hits + misses + incompat
    if lookups <= 0:
        return                      # no persistent tier in play: quiet
    compiles = int(_ctr(snapshot, 'jax.compiles'))
    entries = int((snapshot.get('gauges') or {})
                  .get('compilecache.entries', 0))
    fix = ("verify the cache dir: `python tools/compilecache.py <dir> "
           "--verify` (CRC + version skew per entry), gc stale entries "
           "(`--gc --keep-bytes N`), and check the process is pointed at "
           "the dir the fleet populates (PADDLE_TPU_COMPILE_CACHE, or "
           "artifact_dir= on register/fit/FleetSupervisor) — a first "
           "boot populates, every later boot must hit")
    if incompat >= int(cold_storm_incompat):
        poisoned = incompat >= max(1, lookups // 2)
        yield _diag(
            'cold_compile_storm', 'critical' if poisoned else 'warning',
            f"{incompat} cached executable(s) rejected at load "
            f"(of {lookups} lookup(s)) — corrupt bytes, CRC mismatch, or "
            "jax/backend version skew; each rejection re-paid a compile "
            "the persistent cache exists to skip",
            fix, incompat=incompat, hits=hits, misses=misses,
            jax_compiles=compiles, cache_entries=entries)
        return
    hit_rate = hits / lookups
    # misses against a near-empty inventory are the populate pass; the
    # storm is missing against a POPULATED dir
    populated = entries > misses
    if populated and hit_rate < float(cold_storm_hit_rate) and \
            compiles >= int(cold_storm_compiles):
        yield _diag(
            'cold_compile_storm', 'warning',
            f"boot compiled {compiles} program(s) with a populated "
            f"persistent cache bound ({entries} entries): hit rate "
            f"{hit_rate:.0%} over {lookups} lookup(s) — the cached set "
            "does not match what this process compiles",
            fix, hit_rate=round(hit_rate, 4), hits=hits, misses=misses,
            jax_compiles=compiles, cache_entries=entries)


def detect_noisy_neighbor(events=None, snapshot=None, cluster=None,
                          noisy_share=NOISY_SHARE,
                          noisy_min_pressure=NOISY_MIN_PRESSURE, **_):
    """One tenant dominates the serving pressure on a shared fleet.

    Pressure = that tenant's sheds (every reason — quota, queue_full,
    page_exhaustion) + SLO violations. Sources, snapshot first (labeled
    ``serving.tenant.shed{tenant=}`` / ``serving.tenant.violations``
    counters), tenant-stamped ``serving.shed`` / ``serving.request``
    events filling what the snapshot lacks — max of the two per tenant,
    never the sum. Needs >= 2 tenants with traffic (a single-tenant
    engine owning 100% of its own sheds is ``serving_overload``'s
    business, not a neighbor problem). Victim evidence (the worst other
    tenant's violations / event-path p99) rides along when present."""
    sheds, violations, requests = {}, {}, {}
    if snapshot is not None:
        ctr = snapshot.get('counters')
        sheds.update(_labeled(ctr, 'serving.tenant.shed', key='tenant'))
        violations.update(_labeled(ctr, 'serving.tenant.violations',
                                   key='tenant'))
        requests.update(_labeled(ctr, 'serving.tenant.requests',
                                 key='tenant'))
    ev_sheds, ev_viol, ev_reqs, ev_lat = {}, {}, {}, {}
    for e in (events or []):
        ten = e.get('tenant')
        if ten is None:
            continue
        ten = str(ten)
        if e.get('ev') == 'serving.shed':
            ev_sheds[ten] = ev_sheds.get(ten, 0) + 1
        elif e.get('ev') == 'serving.request':
            ev_reqs[ten] = ev_reqs.get(ten, 0) + 1
            if e.get('status') not in (None, 'ok'):
                ev_viol[ten] = ev_viol.get(ten, 0) + 1
            if isinstance(e.get('latency_ms'), (int, float)):
                ev_lat.setdefault(ten, []).append(float(e['latency_ms']))
    for src, dst in ((ev_sheds, sheds), (ev_viol, violations),
                     (ev_reqs, requests)):
        for ten, n in src.items():
            dst[ten] = max(dst.get(ten, 0), n)
    tenants = set(requests) | set(sheds) | set(violations)
    if len(tenants) < 2:
        return
    pressure = {t: sheds.get(t, 0) + violations.get(t, 0) for t in tenants}
    total = sum(pressure.values())
    if total < noisy_min_pressure:
        return
    noisy, p = max(pressure.items(), key=lambda kv: (kv[1], kv[0]))
    share = p / total
    if share < noisy_share:
        return
    victims = {t: v for t, v in pressure.items() if t != noisy}
    victim = max(victims, key=lambda t: (victims[t],
                                         len(ev_lat.get(t, [])))) \
        if victims else None
    evidence = {'tenant': noisy, 'share': round(share, 3),
                'sheds': int(sheds.get(noisy, 0)),
                'violations': int(violations.get(noisy, 0)),
                'pressure_total': int(total),
                'per_tenant_pressure': {t: int(v) for t, v
                                        in sorted(pressure.items())}}
    detail = (f"tenant {noisy!r} accounts for {share:.0%} of the serving "
              f"pressure ({int(p)} of {int(total)} sheds+violations) on a "
              f"fleet shared by {len(tenants)} tenants")
    if victim is not None and ev_lat.get(victim):
        lat = sorted(ev_lat[victim])
        p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
        detail += (f"; tenant {victim!r} is collateral "
                   f"(p99 {p99:.1f}ms over {len(lat)} request(s))")
        evidence['victim'] = victim
        evidence['victim_p99_ms'] = round(p99, 3)
    severity = 'critical' if share >= (1 + noisy_share) / 2 else 'warning'
    yield _diag(
        'noisy_neighbor', severity, detail,
        f"cap tenant {noisy!r}: register a TenantPolicy with a tighter "
        "token bucket (rate=/burst=) so its overflow sheds as 'quota' at "
        "the front door instead of consuming shared queue/page capacity, "
        "and drop its weight= so weighted-fair admission stops favoring "
        "it; if the tenant is legitimately hot, scale the fleet "
        "(FleetAutoscaler) instead of letting it starve its neighbors",
        **evidence)


def detect_autoscale_flap(events=None, snapshot=None, cluster=None,
                          flap_reversals=FLAP_REVERSALS,
                          flap_window_cooldowns=FLAP_WINDOW_COOLDOWNS,
                          **_):
    """The replica count is oscillating: ``fleet.autoscale`` grow/shrink
    actions keep reversing direction within a few cooldown windows. A
    correctly configured autoscaler cannot do this — the hysteresis band
    means one signal value never justifies both directions, and the
    cooldown + fresh-sustain window spaces opposing actions out — so
    firing means the band is degenerate (burn_low ~ burn_high), cooldown
    is ~0, the pressure signal itself whipsaws across both thresholds
    slower than the window (undersized sustain_ticks), or two
    controllers are fighting (e.g. an autoscaler shrinking replicas a
    supervisor keeps resurrecting). Counter fallback: both
    ``fleet.autoscale.grows`` and ``.shrinks`` high with no event
    timeline still warns."""
    acts = []
    for e in (events or []):
        if e.get('ev') == 'fleet.autoscale' and \
                e.get('action') in ('grow', 'shrink'):
            acts.append((e['action'], int(e.get('tick', 0)),
                         int(e.get('cooldown_ticks', 0))))
    reversals = 0
    pairs = []
    for (a1, t1, _c1), (a2, t2, c2) in zip(acts, acts[1:]):
        window = max(1, c2) * flap_window_cooldowns
        if a1 != a2 and (t2 - t1) <= window:
            reversals += 1
            pairs.append({'from': a1, 'to': a2, 'tick_gap': t2 - t1,
                          'window': window})
    if reversals >= flap_reversals:
        severity = 'critical' if reversals >= 2 * flap_reversals \
            else 'warning'
        yield _diag(
            'autoscale_flap', severity,
            f"the fleet reversed scaling direction {reversals} time(s) "
            f"within {flap_window_cooldowns} cooldown window(s) "
            f"({len(acts)} grow/shrink action(s) total) — capacity is "
            "oscillating, every cycle paying replica boot + drain for "
            "nothing",
            "widen the autoscaler's hysteresis band (burn_low well below "
            "burn_high), raise cooldown_ticks and sustain_ticks so one "
            "noisy burst cannot justify an action, and check nothing "
            "else is mutating the same fleet (a FleetSupervisor "
            "resurrecting replicas the autoscaler drains, or two "
            "autoscalers on one router)",
            reversals=reversals, actions=len(acts),
            recent_reversals=pairs[-3:])
        return
    if not acts and snapshot is not None:
        grows = _ctr(snapshot, 'fleet.autoscale.grows')
        shrinks = _ctr(snapshot, 'fleet.autoscale.shrinks')
        if min(grows, shrinks) >= flap_reversals:
            yield _diag(
                'autoscale_flap', 'warning',
                f"{int(grows)} grow(s) and {int(shrinks)} shrink(s) in "
                "one window with no event timeline to order them — the "
                "fleet is likely oscillating",
                "enable the event log for the timeline, then widen the "
                "autoscaler's hysteresis band / raise cooldown_ticks "
                "(see the fleet.autoscale events for which signal "
                "crossings drove each action)",
                grows=int(grows), shrinks=int(shrinks))


DETECTORS = {
    'straggler': detect_straggler,
    'retrace_storm': detect_retrace_storm,
    'input_bound': detect_input_bound,
    'serving_overload': detect_serving_overload,
    'kv_page_exhaustion': detect_kv_page_exhaustion,
    'rank_flatline': detect_rank_flatline,
    'memory_pressure': detect_memory_pressure,
    'slo_burn': detect_slo_burn,
    'checkpoint_stall': detect_checkpoint_stall,
    'elastic_downsize': detect_elastic_downsize,
    'replica_flapping': detect_replica_flapping,
    'retry_storm': detect_retry_storm,
    'noisy_neighbor': detect_noisy_neighbor,
    'autoscale_flap': detect_autoscale_flap,
    'cold_compile_storm': detect_cold_compile_storm,
    'lint_debt': detect_lint_debt,
    'page_leak': detect_page_leak,
    'latency_creep': detect_latency_creep,
    'qps_collapse': detect_qps_collapse,
    'compile_creep': detect_compile_creep,
    'perf_regression': detect_perf_regression,
}


def diagnose(events=None, snapshot=None, cluster=None, **cfg):
    """Run every detector; return diagnoses ranked most-severe first."""
    out = []
    for name, det in DETECTORS.items():
        try:
            out.extend(det(events=events, snapshot=snapshot,
                           cluster=cluster, **cfg) or [])
        except Exception as e:   # one broken detector must not mute the rest
            out.append(_diag('doctor_error', 'info',
                             f"detector {name} failed: {e!r}",
                             'report this as a paddle_tpu bug',
                             detector=name))
    out.sort(key=lambda d: (SEVERITY_ORDER.get(d['severity'], 9),
                            d['cause']))
    return out


def run_doctor(events=None, snapshot=None, cluster=None, emit=False, **cfg):
    """``diagnose`` + (optionally) land each diagnosis as a structured
    ``diagnosis`` event on the step-event log (requires the package;
    ``emit=True`` from a path-loaded standalone module is a no-op)."""
    diagnoses = diagnose(events=events, snapshot=snapshot, cluster=cluster,
                         **cfg)
    if emit and diagnoses and __package__:
        from . import events as _events
        for d in diagnoses:
            _events.emit('diagnosis', cause=d['cause'],
                         severity=d['severity'], detail=d['detail'],
                         fix=d['fix'], **{
                             k: v for k, v in d['evidence'].items()
                             if isinstance(v, (int, float, str))})
    return diagnoses


def render_report(diagnoses):
    """Operator-facing ranked text report."""
    if not diagnoses:
        return 'doctor: no anomalies detected'
    lines = [f"doctor: {len(diagnoses)} finding(s), most severe first"]
    for i, d in enumerate(diagnoses, 1):
        lines.append(f"{i}. [{d['severity'].upper():8s}] {d['cause']}: "
                     f"{d['detail']}")
        lines.append(f"   fix: {d['fix']}")
    return '\n'.join(lines)
