"""Anomaly doctor: streaming detectors over the telemetry spine.

Turns the raw counters/events mission control collects into a NAMED cause
and a fix-it hint. Each detector inspects the merged event stream and/or a
metrics snapshot (single-process or the aggregator's cluster snapshot) and
yields ``Diagnosis`` dicts::

    {'cause': 'straggler', 'severity': 'critical',
     'detail': 'rank 3 mean step 48.1ms vs cluster median 9.7ms (5.0x)',
     'fix': '...', 'evidence': {...}}

Detector catalog (docs/OBSERVABILITY.md has the operator version):

- ``straggler``       per-rank step-time skew in the cluster snapshot —
                      one rank's mean step time >= ``skew_threshold`` x
                      the cluster median (the ``faultinject.slow_rank``
                      failure mode; on hardware: a thermally throttled or
                      mis-scheduled chip).
- ``retrace_storm``   ``jax.compiles`` still growing after the warmup
                      steps (the dynamic-shape / unhashable-capture traps
                      graftlint GL005/GL006 + GL013 lint for statically).
- ``input_bound``     dataloader wait dominates step time — the
                      accelerator starves on host feed.
- ``serving_overload`` shed + deadline-expired requests trending up on the
                      serving event stream / counters — offered load
                      exceeds engine capacity. Page-exhaustion sheds are
                      EXCLUDED (that is memory pressure, not traffic —
                      see ``kv_page_exhaustion``).
- ``kv_page_exhaustion`` the paged KV cache ran out of pages: admission
                      blocked, decode rows stalled, sequences preempted,
                      or queue-full sheds attributed to page starvation.
                      The fix is memory-side (num_pages / page_size /
                      prefix_cache), never replicas or queue capacity.
- ``rank_flatline``   a rank's heartbeat is stale while siblings beat on
                      (wedged collective / dead process).
- ``memory_pressure`` the cost ledger's worst per-program ``peak_bytes``
                      approaches (>= 80%) or exceeds the device memory
                      budget (``PADDLE_TPU_HBM_BUDGET`` or the device's
                      reported limit) — the next bigger batch/sequence
                      OOMs. The fix is memory-side: microbatch, remat,
                      FSDP sharding.
- ``slo_burn``        a served model is burning its latency error budget
                      faster than its objective allows (the SLO tracker's
                      ``burn_rate``; warning at 1x, critical at 5x).
- ``checkpoint_stall`` synchronous checkpoint saves block the training
                      thread for >= 25% of the mean step time — the fix-it
                      is the async save path (``async_=True``), which
                      moves snapshot+commit off the step path.
- ``elastic_downsize`` the world size shrank mid-run: a rank died and the
                      elastic supervisor resumed on the survivors (info —
                      the run survived, but capacity is reduced; names
                      the dead rank from the supervisor's heartbeats).
- ``replica_flapping`` a serving replica's circuit breaker opened >=
                      ``flap_opens`` times this window — the half-open
                      gate keeps re-admitting a replica that is not
                      better (cold rejoin without warmup, flaky host);
                      the fix-it names the replica and the half-open
                      warmup knobs.
- ``retry_storm``     router failover retries >= 20% of offered load —
                      retry amplification melting the surviving
                      replicas; fix the failing replica, then bound
                      max_retries / hedging and let the shed ladder
                      engage first.
- ``lint_debt``       the tree's justified graftlint waivers (inline
                      ``graftlint: disable`` + ``[[graftlint.waiver]]``
                      blocks) outgrew the ``lint_debt_threshold`` budget
                      recorded in graftlint.toml (info — the lint gate
                      still passes; this flags the creeping debt).

Ranked output: ``critical`` > ``warning`` > ``info``. Standalone on
purpose — stdlib-only, importable by path — so ``tools/doctor.py`` works
with no jax installed. When imported as part of the package,
``run_doctor(..., emit=True)`` also lands each diagnosis as a structured
``diagnosis`` event on the step-event log.
"""

__all__ = ['diagnose', 'run_doctor', 'render_report', 'DETECTORS',
           'SEVERITY_ORDER']

SEVERITY_ORDER = {'critical': 0, 'warning': 1, 'info': 2}

# tunables (detectors take overrides via **cfg)
SKEW_THRESHOLD = 1.75          # rank mean step vs cluster median
WARMUP_STEPS = 5               # compiles inside warmup are expected
RETRACE_GRACE = 3              # compiles beyond warmup that are tolerated
INPUT_BOUND_RATIO = 0.5        # dataloader wait / step time
OVERLOAD_RATIO = 0.05          # (shed + expired) / offered
STALE_HEARTBEAT_S = 10.0
MEMORY_PRESSURE_RATIO = 0.8    # worst program peak_bytes / memory budget
SLO_BURN_WARNING = 1.0         # error-budget burn rate thresholds
SLO_BURN_CRITICAL = 5.0
CHECKPOINT_STALL_RATIO = 0.25  # mean save stall / mean step time
FLAP_OPENS = 4                 # circuit opens per window = flapping
RETRY_STORM_RATIO = 0.2        # router retries / offered requests
RETRY_STORM_MIN = 10           # offered requests before the ratio counts


def _labeled(section, prefix, key='model'):
    """``{label_value: number}`` from snapshot keys shaped
    ``prefix{key=value}`` (the registry's labeled-instrument spelling).
    These families carry exactly ONE label key, so everything between
    ``key=`` and the closing brace IS the value — no comma split, which
    would truncate values that legitimately contain commas (the
    Executor's ``executor.p1[4x8,16x2]`` shape-signature labels)."""
    out = {}
    marker = prefix + '{' + key + '='
    for k, v in (section or {}).items():
        if k.startswith(marker) and k.endswith('}') and \
                isinstance(v, (int, float)):
            out[k[len(marker):-1]] = v
    return out


def _diag(cause, severity, detail, fix, **evidence):
    return {'cause': cause, 'severity': severity, 'detail': detail,
            'fix': fix, 'evidence': evidence}


def _hist(snapshot, name):
    return (snapshot or {}).get('histograms', {}).get(name) or {}


def _ctr(snapshot, name):
    return (snapshot or {}).get('counters', {}).get(name, 0)


# -- detectors --------------------------------------------------------------

def detect_straggler(events=None, snapshot=None, cluster=None,
                     skew_threshold=SKEW_THRESHOLD, **_):
    """Per-rank step-time skew from the cluster snapshot (>= 2 ranks with
    steps). Falls back to rank-stamped ``step`` events when no snapshot
    carries step histograms."""
    per_rank = {}
    if cluster:
        for rank, row in (cluster.get('per_rank') or {}).items():
            st = row.get('step_ms') or {}
            if st.get('count'):
                per_rank[int(rank)] = (float(st.get('mean', 0.0)),
                                       int(st['count']))
    if not per_rank and events:
        sums = {}
        for e in events:
            if e.get('ev') == 'step' and isinstance(
                    e.get('step_ms'), (int, float)) and 'rank' in e:
                s, n = sums.get(int(e['rank']), (0.0, 0))
                sums[int(e['rank'])] = (s + float(e['step_ms']), n + 1)
        per_rank = {r: (s / n, n) for r, (s, n) in sums.items() if n}
    if len(per_rank) < 2:
        return
    means = sorted(m for m, _n in per_rank.values())
    # lower median: with an even rank count the upper middle can BE the
    # straggler, hiding the skew
    median = means[(len(means) - 1) // 2]
    if median <= 0:
        return
    worst_rank, (worst_mean, worst_n) = max(
        per_rank.items(), key=lambda kv: kv[1][0])
    skew = worst_mean / median
    if skew < skew_threshold:
        return
    yield _diag(
        'straggler', 'critical',
        f"rank {worst_rank} mean step {worst_mean:.1f}ms vs cluster median "
        f"{median:.1f}ms ({skew:.1f}x) over {worst_n} step(s)",
        "inspect that rank's lane in merged_trace.json: a slow host "
        "(input pipeline, checkpoint I/O) shows host-side spans stretching; "
        "a slow chip shows uniform step stretch — reschedule the rank or "
        "drop it via elastic restart",
        rank=worst_rank, mean_step_ms=round(worst_mean, 3),
        median_step_ms=round(median, 3), skew=round(skew, 3),
        per_rank_mean_step_ms={r: round(m, 3)
                               for r, (m, _n) in sorted(per_rank.items())})


def detect_retrace_storm(events=None, snapshot=None, cluster=None,
                         warmup_steps=WARMUP_STEPS,
                         retrace_grace=RETRACE_GRACE, **_):
    """Compile count growth after warmup: in steady state every step reuses
    the cached program, so compiles beyond the warmed-up set mean the shape
    or hash key keeps changing (GL005/GL006/GL013 territory)."""
    rows = []
    if cluster:
        for rank, row in (cluster.get('per_rank') or {}).items():
            rows.append((f"rank {rank}", int(row.get('steps') or 0),
                         int(row.get('jax_compiles') or 0)))
    elif snapshot is not None:
        steps = int(_ctr(snapshot, 'hapi.steps')
                    or _hist(snapshot, 'hapi.step_ms').get('count', 0))
        rows.append(('process', steps, int(_ctr(snapshot, 'jax.compiles'))))
    for who, steps, compiles in rows:
        if steps <= warmup_steps:
            continue
        excess = compiles - warmup_steps - retrace_grace
        if excess <= 0 or compiles < 0.5 * steps:
            continue
        yield _diag(
            'retrace_storm', 'critical',
            f"{who}: {compiles} XLA compile(s) over {steps} step(s) — "
            "steady state should compile ~once; something retraces every "
            "step",
            "a traced argument's shape/dtype/hash changes per call: run "
            "`python -m paddle_tpu.analysis` (GL005/GL006 retrace traps, "
            "GL013 unbucketed shapes) and pad dynamic batches with "
            "serving.bucketing",
            who=who, steps=steps, compiles=compiles)


def detect_input_bound(events=None, snapshot=None, cluster=None,
                       input_bound_ratio=INPUT_BOUND_RATIO, **_):
    """Dataloader wait dominating step time: the device idles on host
    feed. Uses histogram sums (wait vs step) per process/cluster, plus the
    streamed ``input_stall`` events as corroborating evidence."""
    rows = []
    if cluster:
        for rank, row in (cluster.get('per_rank') or {}).items():
            st = row.get('step_ms') or {}
            step_sum = float(st.get('mean', 0.0)) * int(st.get('count') or 0)
            rows.append((f"rank {rank}",
                         float(row.get('dataloader_wait_ms_sum') or 0.0),
                         step_sum))
    elif snapshot is not None:
        rows.append(('process',
                     float(_hist(snapshot,
                                 'dataloader.next_wait_ms').get('sum', 0.0)),
                     float(_hist(snapshot, 'hapi.step_ms').get('sum', 0.0))))
    stalls = sum(1 for e in (events or []) if e.get('ev') == 'input_stall')
    for who, wait_ms, step_ms in rows:
        if step_ms <= 0 or wait_ms <= 0:
            continue
        ratio = wait_ms / step_ms
        if ratio < input_bound_ratio:
            continue
        yield _diag(
            'input_bound', 'warning',
            f"{who}: dataloader wait {wait_ms:.0f}ms is "
            f"{100 * ratio:.0f}% of step time {step_ms:.0f}ms — the step "
            "starves on host feed",
            "raise DataLoader num_workers / prefetch depth, move decode or "
            "augmentation off the step path, or shard the input files "
            "wider; dataloader.queue_depth should sit near its capacity",
            who=who, wait_ms=round(wait_ms, 1), step_ms=round(step_ms, 1),
            ratio=round(ratio, 3), input_stall_events=stalls)


def detect_serving_overload(events=None, snapshot=None, cluster=None,
                            overload_ratio=OVERLOAD_RATIO, **_):
    """Load shedding / deadline expiry trending up on the serving stream:
    offered load exceeds what the engine drains."""
    counters = (cluster or {}).get('counters_total') if cluster else None
    if counters is None and snapshot is not None:
        counters = {
            'serving_requests': _ctr(snapshot, 'serving.requests'),
            'serving_shed': _ctr(snapshot, 'serving.shed'),
            'serving_shed_page_exhaustion': _ctr(
                snapshot, 'serving.shed.page_exhaustion'),
            'serving_deadline_expired': _ctr(snapshot,
                                             'serving.deadline_expired'),
        }
    # serving.requests counts every submission (sheds included), so it IS
    # the offered load; the event stream reconstructs the same totals when
    # no counter snapshot is available. Page-exhaustion sheds are memory
    # pressure wearing a queue-full mask — kv_page_exhaustion owns those,
    # and counting them here would prescribe replicas for an OOM.
    offered = shed = expired = page_shed = 0
    if counters:
        offered = int(counters.get('serving_requests') or 0)
        shed = int(counters.get('serving_shed') or 0)
        page_shed = int(counters.get('serving_shed_page_exhaustion') or 0)
        expired = int(counters.get('serving_deadline_expired') or 0)
    if events:
        ev_shed = sum(1 for e in events if e.get('ev') == 'serving.shed')
        ev_pshed = sum(1 for e in events if e.get('ev') == 'serving.shed'
                       and e.get('reason') == 'page_exhaustion')
        ev_exp = sum(1 for e in events if e.get('ev') == 'serving.request'
                     and e.get('status') == 'deadline')
        ev_req = sum(1 for e in events if e.get('ev') == 'serving.request')
        shed = max(shed, ev_shed)
        page_shed = max(page_shed, ev_pshed)
        expired = max(expired, ev_exp)
        offered = max(offered, ev_req + ev_shed)
    shed = max(0, shed - page_shed)
    bad = shed + expired
    if not offered or not bad:
        return
    ratio = bad / offered
    if ratio < overload_ratio:
        return
    yield _diag(
        'serving_overload', 'warning' if ratio < 0.25 else 'critical',
        f"{bad} of {offered} request(s) shed or deadline-expired "
        f"({100 * ratio:.0f}%) — offered load exceeds engine capacity",
        "add engine replicas or raise queue_capacity only with more "
        "compute behind it; widen the bucket set so batches fill, or "
        "lower client deadlines so doomed work is shed at admission "
        "instead of after queueing",
        offered=offered, shed=shed, deadline_expired=expired,
        ratio=round(ratio, 3))


def detect_kv_page_exhaustion(events=None, snapshot=None, cluster=None, **_):
    """The paged KV cache ran out of pages: admission blocked behind page
    starvation (sheds attributed ``page_exhaustion``), decode rows
    stalled, or sequences were preempted to free memory. Distinct from
    ``serving_overload`` on purpose — the fix is pages, not replicas."""
    counters = (cluster or {}).get('counters_total') if cluster else None
    if counters is None and snapshot is not None:
        counters = {
            'serving_shed_page_exhaustion': _ctr(
                snapshot, 'serving.shed.page_exhaustion'),
            'serving_kv_decode_stalls': _ctr(snapshot,
                                             'serving.kv.decode_stalls'),
            'serving_kv_prefill_stalls': _ctr(snapshot,
                                              'serving.kv.prefill_stalls'),
            'serving_preemptions': _ctr(snapshot, 'serving.preemptions'),
        }
    page_shed = stalls = preempts = 0
    if counters:
        page_shed = int(counters.get('serving_shed_page_exhaustion') or 0)
        stalls = (int(counters.get('serving_kv_decode_stalls') or 0) +
                  int(counters.get('serving_kv_prefill_stalls') or 0))
        preempts = int(counters.get('serving_preemptions') or 0)
    if events:
        page_shed = max(page_shed, sum(
            1 for e in events if e.get('ev') == 'serving.shed'
            and e.get('reason') == 'page_exhaustion'))
        stalls = max(stalls, sum(
            1 for e in events if e.get('ev') == 'serving.page_exhausted'))
        preempts = max(preempts, sum(
            1 for e in events if e.get('ev') == 'serving.preempt'))
    if not (page_shed or stalls or preempts):
        return
    util = None
    if snapshot is not None:
        util = (snapshot.get('gauges') or {}).get(
            'serving.kv.page_utilization')
    severity = 'critical' if (page_shed or preempts) else 'warning'
    yield _diag(
        'kv_page_exhaustion', severity,
        f"paged KV cache out of pages: {page_shed} shed(s) attributed to "
        f"page exhaustion, {stalls} stall(s), {preempts} preemption(s)"
        + (f" at {100 * util:.0f}% page utilization"
           if isinstance(util, (int, float)) else ""),
        "grow num_pages (or shrink page_size to cut tail waste), enable "
        "prefix_cache= for shared system prompts, or lower "
        "max_new_tokens/deadlines; raising queue_capacity or adding "
        "replicas will NOT help — memory, not traffic, is the limit",
        page_exhaustion_sheds=page_shed, stalls=stalls,
        preemptions=preempts,
        **({'page_utilization': round(util, 4)}
           if isinstance(util, (int, float)) else {}))


def detect_rank_flatline(events=None, snapshot=None, cluster=None,
                         stale_heartbeat_s=STALE_HEARTBEAT_S, **_):
    """A rank whose heartbeat went stale while siblings stay fresh: a
    wedged collective or a dead process the deadline layer hasn't named
    yet."""
    ages = (cluster or {}).get('heartbeat_age_s') or {}
    fresh = [r for r, a in ages.items()
             if a is not None and a < stale_heartbeat_s]
    for rank, age in sorted(ages.items()):
        if age is None or age < stale_heartbeat_s or not fresh:
            continue
        yield _diag(
            'rank_flatline', 'critical',
            f"rank {rank} heartbeat is {age:.1f}s stale while "
            f"{len(fresh)} sibling(s) beat on — wedged or dead rank",
            "the supervisor's fail-fast should fire shortly; if not, check "
            "distributed.set_timeout (collective deadline) and the rank's "
            "stderr log in the run dir",
            rank=rank, heartbeat_age_s=age, fresh_ranks=sorted(fresh))


def detect_memory_pressure(events=None, snapshot=None, cluster=None,
                           hbm_budget=None,
                           memory_pressure_ratio=MEMORY_PRESSURE_RATIO, **_):
    """Worst per-program peak memory vs. the device budget, from the cost
    ledger's ``cost.peak_bytes{program=}`` gauges (snapshot) or
    ``cost.program`` events. Budget: the ``hbm_budget`` override, the
    ``PADDLE_TPU_HBM_BUDGET`` env (bytes), or — when jax is importable,
    which it is not from the path-loaded tools — the device's reported
    ``bytes_limit``."""
    import os
    budget = hbm_budget
    if budget is None:
        raw = os.environ.get('PADDLE_TPU_HBM_BUDGET', '')
        if raw:
            try:
                budget = int(float(raw))
            except ValueError:
                budget = None
    if budget is None:
        try:
            import jax
            stats = jax.devices()[0].memory_stats() or {}
            budget = int(stats.get('bytes_limit') or 0) or None
        except Exception:
            budget = None
    if not budget:
        return
    peaks = {}
    if snapshot is not None:
        peaks.update(_labeled(snapshot.get('gauges'), 'cost.peak_bytes',
                              key='program'))
    for e in (events or []):
        if e.get('ev') == 'cost.program' and isinstance(
                e.get('peak_bytes'), (int, float)):
            name = str(e.get('program', '?'))
            peaks[name] = max(peaks.get(name, 0), float(e['peak_bytes']))
    if not peaks:
        return
    worst_prog, worst = max(peaks.items(), key=lambda kv: kv[1])
    ratio = worst / budget
    if ratio < memory_pressure_ratio:
        return
    yield _diag(
        'memory_pressure', 'critical' if ratio >= 1.0 else 'warning',
        f"program {worst_prog!r} peaks at {worst / 1e6:.1f} MB = "
        f"{100 * ratio:.0f}% of the {budget / 1e6:.1f} MB device budget"
        + (" — it does not fit" if ratio >= 1.0 else
           " — the next bigger batch/sequence will not fit"),
        "cut live memory: engine.build_train_step(microbatch=k) to shrink "
        "the per-dispatch batch, remat='dots'/'full' to trade FLOPs for "
        "activations, sharding= (FSDP) to split params/optimizer state "
        "across the mesh, or page the serving KV cache down; raise "
        "PADDLE_TPU_HBM_BUDGET only if the budget was set conservatively",
        program=worst_prog, peak_bytes=int(worst), budget_bytes=int(budget),
        ratio=round(ratio, 4))


def detect_slo_burn(events=None, snapshot=None, cluster=None,
                    slo_burn_warning=SLO_BURN_WARNING,
                    slo_burn_critical=SLO_BURN_CRITICAL, **_):
    """Error-budget burn per served model, from the SLO tracker's
    ``slo.burn_rate{model=}`` gauge (snapshot) or the ``slo.violation``
    event stream. The gauge WINS where both exist: it is updated on every
    request, while a violation event carries the burn at emission — stale
    the moment good requests follow — so events only fill models the
    snapshot does not cover (bare event-log runs, flight dumps). Counts
    likewise take the max of the two sources, never their sum."""
    burns = {}
    counts = {}
    if snapshot is not None:
        burns.update(_labeled(snapshot.get('gauges'), 'slo.burn_rate'))
        counts.update(_labeled(snapshot.get('counters'), 'slo.violations'))
    ev_burns, ev_counts = {}, {}
    for e in (events or []):
        if e.get('ev') == 'slo.violation' and isinstance(
                e.get('burn_rate'), (int, float)):
            model = str(e.get('model', '?'))
            ev_burns[model] = float(e['burn_rate'])  # stream: last wins
            ev_counts[model] = ev_counts.get(model, 0) + 1
    for model, b in ev_burns.items():
        burns.setdefault(model, b)
    for model, n in ev_counts.items():
        counts[model] = max(counts.get(model, 0), n)
    for model, burn in sorted(burns.items()):
        if burn < slo_burn_warning:
            continue
        severity = 'critical' if burn >= slo_burn_critical else 'warning'
        yield _diag(
            'slo_burn', severity,
            f"model {model!r} is burning its latency error budget at "
            f"{burn:.1f}x the sustainable rate"
            + (f" ({int(counts[model])} violation(s))"
               if counts.get(model) else ""),
            "cut tail latency (widen buckets so batches fill, shrink "
            "max_new_tokens/deadlines, add prefix caching) or add "
            "capacity; if the objective is wrong, re-register with a "
            "realistic slo_ms — burning quietly hides real regressions",
            model=model, burn_rate=round(burn, 3),
            violations=int(counts.get(model, 0)))


def detect_checkpoint_stall(events=None, snapshot=None, cluster=None,
                            checkpoint_stall_ratio=CHECKPOINT_STALL_RATIO,
                            **_):
    """Checkpoint saves stalling the training thread: the mean
    ``checkpoint.save_stall_ms`` (training-thread blocked time — the full
    commit for synchronous saves, ~0 for async ones) is a large fraction
    of the mean step time. The fix is the async save path, not a faster
    disk."""
    stall_mean = stall_count = step_mean = 0.0
    if snapshot is not None:
        h = _hist(snapshot, 'checkpoint.save_stall_ms')
        stall_mean, stall_count = float(h.get('mean', 0.0)), \
            int(h.get('count') or 0)
        for name in ('hapi.step_ms', 'engine.step_ms'):
            sh = _hist(snapshot, name)
            if sh.get('count'):
                step_mean = float(sh.get('mean', 0.0))
                break
    if (not stall_count or not step_mean) and events:
        # event-stream fallback: synchronous saves' commit time IS their
        # stall; async saves are excluded (their stall is the enqueue)
        durs = [float(e['duration_ms']) for e in events
                if e.get('ev') == 'checkpoint.save'
                and not e.get('async_')
                and isinstance(e.get('duration_ms'), (int, float))]
        steps = [float(e['step_ms']) for e in events
                 if e.get('ev') == 'step'
                 and isinstance(e.get('step_ms'), (int, float))]
        if durs and steps:
            stall_mean = sum(durs) / len(durs)
            stall_count = len(durs)
            step_mean = sum(steps) / len(steps)
    if not stall_count or step_mean <= 0 or stall_mean <= 0:
        return
    ratio = stall_mean / step_mean
    if ratio < checkpoint_stall_ratio:
        return
    yield _diag(
        'checkpoint_stall', 'warning',
        f"checkpoint saves stall the training thread {stall_mean:.1f}ms "
        f"on average = {100 * ratio:.0f}% of the {step_mean:.1f}ms mean "
        f"step, over {stall_count} save(s)",
        "use the async save path: CheckpointManager.save(async_=True), "
        "CheckpointSaver(async_save=True), or engine.fit(checkpoint=..., "
        "async_save=True) — the snapshot+commit move to a background "
        "thread and checkpoint.save_stall_ms drops to ~0 "
        "(checkpoint.commit_ms keeps the true disk latency)",
        stall_ms=round(stall_mean, 3), step_ms=round(step_mean, 3),
        ratio=round(ratio, 3), saves=stall_count)


def detect_elastic_downsize(events=None, snapshot=None, cluster=None, **_):
    """The world size shrank mid-run: a rank died and the elastic
    supervisor re-formed the mesh with the survivors instead of
    fail-fasting. Informational by design — the run SURVIVED — but every
    downsize means less throughput and one less failure the budget can
    absorb, so it must never pass silently."""
    downs = [e for e in (events or [])
             if e.get('ev') == 'elastic.downsize']
    count = len(downs)
    for src in (snapshot, None if cluster is None else
                {'counters': cluster.get('counters_total') or {}}):
        if src is not None:
            count = max(count, int(_ctr(
                src, 'distributed.elastic_downsizes') or 0))
    if not count:
        return
    recov = _hist(snapshot, 'elastic.recovery_ms') if snapshot else {}
    for e in downs or [{}]:
        dead = e.get('dead_rank')
        detail = (f"world shrank {e.get('old_world', '?')} -> "
                  f"{e.get('new_world', '?')}"
                  + (f" after rank {dead} died"
                     + (f" ({e['signal']})" if e.get('signal') else "")
                     if dead is not None else "")) if e else \
            f"{count} elastic downsize(s) this run"
        yield _diag(
            'elastic_downsize', 'info', detail,
            "the job survived on fewer ranks; restore full capacity by "
            "bringing a replacement up inside the rejoin grace window "
            "(rejoin_<rank> marker / a rescheduled node), or expect "
            "proportionally lower throughput until the next full restart",
            downsizes=count,
            **({'dead_rank': dead} if e and dead is not None else {}),
            **({'recovery_ms_p50': round(recov['p50'], 1)}
               if recov.get('count') else {}))
        if not e:
            break


def detect_replica_flapping(events=None, snapshot=None, cluster=None,
                            flap_opens=FLAP_OPENS, **_):
    """A serving replica's circuit breaker is oscillating: it opened
    ``flap_opens``+ times this window (``serving.router.circuit``
    events), usually with closes in between — the half-open probe window
    keeps re-admitting a replica that is not actually better (cold
    compile storm on rejoin, flaky host, undersized warmup), so live
    traffic keeps paying the failure tax."""
    opens, closes, last_reason = {}, {}, {}
    for e in (events or []):
        if e.get('ev') != 'serving.router.circuit':
            continue
        rep = str(e.get('replica', '?'))
        if e.get('state') == 'open':
            opens[rep] = opens.get(rep, 0) + 1
            if e.get('reason'):
                last_reason[rep] = str(e['reason'])
        elif e.get('state') == 'closed':
            closes[rep] = closes.get(rep, 0) + 1
    if not opens:
        # last-wins router_stats fallback (flight dumps with a short
        # event window): lifetime trip counts, no close attribution
        for e in reversed(events or []):
            if e.get('ev') == 'serving.router_stats':
                for rep, row in (e.get('replicas') or {}).items():
                    if isinstance(row, dict) and row.get('trips'):
                        opens[str(rep)] = int(row['trips'])
                break
    for rep, n in sorted(opens.items()):
        if n < flap_opens:
            continue
        severity = 'critical' if n >= 2 * flap_opens else 'warning'
        yield _diag(
            'replica_flapping', severity,
            f"replica {rep!r} circuit opened {n} time(s)"
            + (f", closed {closes[rep]} time(s)" if closes.get(rep) else "")
            + (f" (last trip: {last_reason[rep]})"
               if last_reason.get(rep) else "")
            + " — it keeps being re-admitted and keeps failing",
            f"stop the flap at replica {rep!r}: lengthen its half-open "
            "warmup (raise RouterPolicy.half_open_probes and "
            "circuit_cooldown_s so a rejoining replica proves itself on "
            "more probes before taking real traffic), make sure the "
            "relaunch path calls warmup() so probes don't hit a cold "
            "compile storm, and if it still trips, drain() it and "
            "inspect the host instead of letting the breaker babysit it",
            replica=rep, opens=n, closes=int(closes.get(rep, 0)),
            **({'last_trip': last_reason[rep]}
               if last_reason.get(rep) else {}))


def detect_retry_storm(events=None, snapshot=None, cluster=None,
                       retry_storm_ratio=RETRY_STORM_RATIO,
                       retry_storm_min=RETRY_STORM_MIN, **_):
    """Router failover retries are a large fraction of offered load —
    retry amplification: every failed request multiplies into several
    dispatched ones, which is exactly how a degraded fleet melts the
    healthy replicas too. Offered = first-attempt dispatches (dispatched
    minus retries minus hedges); fires at ``retries/offered >=``
    ``retry_storm_ratio`` once at least ``retry_storm_min`` requests were
    offered."""
    dispatched = retries = hedges = 0
    if snapshot is not None:
        # per-replica labeled families (one label set per family): the
        # fleet total is the sum over replica labels
        ctrs = snapshot.get('counters')
        dispatched = int(sum(_labeled(
            ctrs, 'serving.router.dispatched', key='replica').values()))
        retries = int(sum(_labeled(
            ctrs, 'serving.router.retries', key='replica').values()))
        hedges = int(sum(_labeled(
            ctrs, 'serving.router.hedges', key='replica').values()))
    if not dispatched:
        for e in reversed(events or []):   # last-wins cumulative event
            if e.get('ev') == 'serving.router_stats':
                for row in (e.get('replicas') or {}).values():
                    if isinstance(row, dict):
                        dispatched += int(row.get('dispatched') or 0)
                        retries += int(row.get('retried') or 0)
                        hedges += int(row.get('hedged') or 0)
                break
    offered = dispatched - retries - hedges
    if offered < retry_storm_min or retries <= 0:
        return
    ratio = retries / offered
    if ratio < retry_storm_ratio:
        return
    severity = 'critical' if ratio >= 2 * retry_storm_ratio else 'warning'
    yield _diag(
        'retry_storm', severity,
        f"{retries} failover retries on {offered} offered request(s) = "
        f"{100 * ratio:.0f}% amplification — the fleet is re-dispatching "
        "a large share of its load onto the surviving replicas",
        "find WHY requests fail over (serving.router.failover events and "
        "the circuit log name the replica) and fix that replica; then "
        "bound the blast radius — lower RouterPolicy.max_retries, keep "
        "hedging for tail latency only (hedge_after_ms near p95, not "
        "p50), and check the shed ladder thresholds engage before "
        "retries do, so overload sheds instead of amplifying",
        dispatched=dispatched, retries=retries, hedges=hedges,
        offered=offered, ratio=round(ratio, 3))


def detect_lint_debt(events=None, snapshot=None, cluster=None,
                     lint_debt_threshold=None, repo_root=None, **_):
    """The repo's justified-waiver count outgrew the budget recorded in
    ``graftlint.toml`` (``lint_debt_threshold``). Every waiver is a rule
    firing that somebody argued around; past the budget the arguing is
    the norm and the linter has stopped steering. Info-only: the gate
    (tier-1 lint) still passes — this names the creeping debt before a
    waiver-heavy PR normalizes it. Quiet when no budget is recorded or
    the tree is not checked out (installed package without sources)."""
    import os
    import re
    root = repo_root
    if root is None:
        here = os.path.dirname(os.path.abspath(__file__))
        root = os.path.dirname(os.path.dirname(here))
    toml = os.path.join(root, 'graftlint.toml')
    if not os.path.isfile(toml):
        return
    try:
        with open(toml, 'r', encoding='utf-8') as f:
            cfg_text = f.read()
    except OSError:
        return
    if lint_debt_threshold is None:
        m = re.search(r'^\s*lint_debt_threshold\s*=\s*(\d+)', cfg_text,
                      re.MULTILINE)
        if m is None:
            return
        lint_debt_threshold = int(m.group(1))
    file_waivers = len(re.findall(r'\[\[graftlint\.waiver\]\]', cfg_text))
    inline = 0
    pkg = os.path.join(root, 'paddle_tpu')
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != '__pycache__']
        for fn in filenames:
            if not fn.endswith('.py'):
                continue
            try:
                with open(os.path.join(dirpath, fn), 'r',
                          encoding='utf-8') as f:
                    inline += len(re.findall(r'#\s*graftlint:\s*disable',
                                             f.read()))
            except OSError:
                continue
    total = file_waivers + inline
    if total <= int(lint_debt_threshold):
        return
    yield _diag(
        'lint_debt', 'info',
        f"{total} graftlint waiver(s) in the tree ({inline} inline, "
        f"{file_waivers} file-level) exceed the lint_debt_threshold="
        f"{lint_debt_threshold} budget recorded in graftlint.toml",
        "burn down the debt before adding to it: re-read the oldest "
        "waivers (git log -S 'graftlint: disable'), fix the ones whose "
        "justification no longer holds, and only then raise "
        "lint_debt_threshold for the remainder that is genuinely "
        "by-design",
        waivers=total, inline=inline, file_level=file_waivers,
        threshold=int(lint_debt_threshold))


DETECTORS = {
    'straggler': detect_straggler,
    'retrace_storm': detect_retrace_storm,
    'input_bound': detect_input_bound,
    'serving_overload': detect_serving_overload,
    'kv_page_exhaustion': detect_kv_page_exhaustion,
    'rank_flatline': detect_rank_flatline,
    'memory_pressure': detect_memory_pressure,
    'slo_burn': detect_slo_burn,
    'checkpoint_stall': detect_checkpoint_stall,
    'elastic_downsize': detect_elastic_downsize,
    'replica_flapping': detect_replica_flapping,
    'retry_storm': detect_retry_storm,
    'lint_debt': detect_lint_debt,
}


def diagnose(events=None, snapshot=None, cluster=None, **cfg):
    """Run every detector; return diagnoses ranked most-severe first."""
    out = []
    for name, det in DETECTORS.items():
        try:
            out.extend(det(events=events, snapshot=snapshot,
                           cluster=cluster, **cfg) or [])
        except Exception as e:   # one broken detector must not mute the rest
            out.append(_diag('doctor_error', 'info',
                             f"detector {name} failed: {e!r}",
                             'report this as a paddle_tpu bug',
                             detector=name))
    out.sort(key=lambda d: (SEVERITY_ORDER.get(d['severity'], 9),
                            d['cause']))
    return out


def run_doctor(events=None, snapshot=None, cluster=None, emit=False, **cfg):
    """``diagnose`` + (optionally) land each diagnosis as a structured
    ``diagnosis`` event on the step-event log (requires the package;
    ``emit=True`` from a path-loaded standalone module is a no-op)."""
    diagnoses = diagnose(events=events, snapshot=snapshot, cluster=cluster,
                         **cfg)
    if emit and diagnoses and __package__:
        from . import events as _events
        for d in diagnoses:
            _events.emit('diagnosis', cause=d['cause'],
                         severity=d['severity'], detail=d['detail'],
                         fix=d['fix'], **{
                             k: v for k, v in d['evidence'].items()
                             if isinstance(v, (int, float, str))})
    return diagnoses


def render_report(diagnoses):
    """Operator-facing ranked text report."""
    if not diagnoses:
        return 'doctor: no anomalies detected'
    lines = [f"doctor: {len(diagnoses)} finding(s), most severe first"]
    for i, d in enumerate(diagnoses, 1):
        lines.append(f"{i}. [{d['severity'].upper():8s}] {d['cause']}: "
                     f"{d['detail']}")
        lines.append(f"   fix: {d['fix']}")
    return '\n'.join(lines)
