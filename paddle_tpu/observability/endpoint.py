"""Live telemetry endpoint: stdlib HTTP server for mission control.

A tiny ``ThreadingHTTPServer`` any runtime can attach — the launch
supervisor, a ``ServingEngine``, or a single-process ``Model.fit`` — that
serves the process's (and, given a run dir, the cluster's) telemetry live
instead of post-hoc:

- ``GET /metrics``    Prometheus text exposition of the process registry;
                      with a run dir attached, also per-rank
                      ``cluster.step_ms`` / ``cluster.heartbeat_age_s``
                      series labeled ``rank=/host=``.
- ``GET /healthz``    JSON: process liveness, uptime, per-rank heartbeat
                      ages; HTTP 503 when any rank's heartbeat is stale
                      (scrapers and load balancers need the status code,
                      not just the body).
- ``GET /events``     JSON tail of the step-event log
                      (``?n=100&ev=step`` filters).
- ``GET /diagnosis``  the anomaly doctor's ranked findings as JSON.
- ``GET /costs``      the cost explorer's ledger slice: per-program
                      FLOPs/bytes/peak memory + roofline estimates, the
                      summary aggregates, and the SLO burn rates.
- ``GET /timeseries`` the ring sampler's timelines: this process's live
                      export plus, with a run dir attached, the cluster
                      merge (``?series=page_util`` substring-filters the
                      series map).

Security posture: binds 127.0.0.1 unless
``PADDLE_TPU_TELEMETRY_HTTP_HOST`` says otherwise — this is a diagnostics
port, not a public service; no auth, read-only GETs. Off by default like
the whole spine: nothing listens unless telemetry is enabled AND a port is
configured (``PADDLE_TPU_TELEMETRY_HTTP``) or ``MetricsServer`` is started
explicitly.
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from . import events, registry, state, timing

__all__ = ['MetricsServer', 'maybe_start_from_env', 'active_server',
           'stop_active_server', 'STALE_HEARTBEAT_S']

STALE_HEARTBEAT_S = 10.0

_lock = threading.Lock()
_active = [None]


class _Handler(BaseHTTPRequestHandler):
    server_version = 'paddle-tpu-telemetry/1'

    # the endpoint must never chat on the training job's stderr
    def log_message(self, format, *args):   # noqa: A002 (stdlib signature)
        pass

    def _send(self, code, body, content_type='application/json'):
        data = body if isinstance(body, bytes) else body.encode('utf-8')
        self.send_response(code)
        self.send_header('Content-Type', content_type)
        self.send_header('Content-Length', str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):   # noqa: N802 (stdlib casing)
        try:
            url = urlparse(self.path)
            route = url.path.rstrip('/') or '/'
            if route == '/metrics':
                self._send(200, self.server.owner.render_metrics(),
                           content_type='text/plain; version=0.0.4; '
                                        'charset=utf-8')
            elif route == '/healthz':
                code, payload = self.server.owner.health()
                self._send(code, json.dumps(payload, sort_keys=True))
            elif route == '/events':
                q = parse_qs(url.query)
                n = int(q.get('n', ['100'])[0])
                kind = q.get('ev', [None])[0]
                evs = events.events()
                if kind:
                    evs = [e for e in evs if e.get('ev') == kind]
                self._send(200, json.dumps(evs[-n:] if n > 0 else [],
                                           default=repr))
            elif route == '/diagnosis':
                self._send(200, json.dumps(self.server.owner.diagnosis(),
                                           sort_keys=True, default=repr))
            elif route == '/costs':
                self._send(200, json.dumps(self.server.owner.costs(),
                                           sort_keys=True, default=repr))
            elif route == '/timeseries':
                q = parse_qs(url.query)
                needle = q.get('series', [None])[0]
                self._send(200, json.dumps(
                    self.server.owner.timeseries(series=needle),
                    sort_keys=True, default=repr))
            else:
                self._send(404, json.dumps(
                    {'error': f'no route {route!r}',
                     'routes': ['/metrics', '/healthz', '/events',
                                '/diagnosis', '/costs', '/timeseries']}))
        except BrokenPipeError:
            pass
        except Exception as e:   # a scrape must never kill the server
            try:
                self._send(500, json.dumps({'error': repr(e)}))
            except OSError:
                pass


class MetricsServer:
    """One live telemetry endpoint for this process.

    ``run_dir``: attach a supervisor run dir to export per-rank series and
    heartbeat health. ``extra_health``: callable returning a dict merged
    into the ``/healthz`` body (e.g. ServingEngine queue depths).
    """

    def __init__(self, host=None, port=None, run_dir=None,
                 extra_health=None, stale_after_s=STALE_HEARTBEAT_S):
        self.host = state.http_host() if host is None else host
        self.port = (state.http_port() or 0) if port is None else int(port)
        self.run_dir = run_dir
        self.extra_health = extra_health
        self.stale_after_s = float(stale_after_s)
        self._httpd = None
        self._thread = None
        self._sw = None

    # -- payload builders (also used by tests, no HTTP needed) -----------
    def _cluster(self):
        if not self.run_dir:
            return None
        from . import aggregate
        return aggregate.cluster_snapshot(self.run_dir)

    def render_metrics(self):
        """Process exposition + per-rank cluster series when attached."""
        text = registry.to_prometheus()
        cluster = self._cluster()
        if not cluster or not cluster['n_ranks']:
            return text
        esc = registry.escape_label_value
        # one family at a time: exposition format requires every sample of
        # a family to be contiguous under its single # TYPE line
        lines = []
        ranks = sorted(cluster['per_rank'].items())
        lines.append('# TYPE paddle_tpu_cluster_step_ms summary')
        for rank, row in ranks:
            lbl = f'rank="{esc(rank)}",host="{esc(row.get("host") or "?")}"'
            st = row.get('step_ms') or {}
            lines.append(f'paddle_tpu_cluster_step_ms_count{{{lbl}}} '
                         f'{int(st.get("count") or 0)}')
            for q, key in (('0.5', 'p50'), ('0.99', 'p99')):
                lines.append(
                    f'paddle_tpu_cluster_step_ms{{{lbl},quantile="{q}"}} '
                    f'{st.get(key, 0.0)}')
        lines.append('# TYPE paddle_tpu_cluster_jax_compiles counter')
        for rank, row in ranks:
            lbl = f'rank="{esc(rank)}",host="{esc(row.get("host") or "?")}"'
            lines.append(f'paddle_tpu_cluster_jax_compiles{{{lbl}}} '
                         f'{int(row.get("jax_compiles") or 0)}')
        lines.append('# TYPE paddle_tpu_cluster_heartbeat_age_s gauge')
        for rank, age in sorted(cluster['heartbeat_age_s'].items()):
            if age is None:
                continue
            lines.append(
                f'paddle_tpu_cluster_heartbeat_age_s{{rank="{esc(rank)}"}} '
                f'{age}')
        return text + '\n'.join(lines) + ('\n' if lines else '')

    def health(self):
        """(http_code, payload): 200 while every known heartbeat is fresh,
        503 once any goes stale — scrape-friendly liveness."""
        import os
        import socket
        payload = {
            'status': 'ok',
            'telemetry_enabled': state.enabled(),
            'pid': os.getpid(),
            'host': socket.gethostname(),
            'uptime_s': round(self._sw.elapsed(), 3) if self._sw else 0.0,
        }
        cluster = self._cluster()
        if cluster is not None:
            ages = cluster.get('heartbeat_age_s') or {}
            payload['heartbeat_age_s'] = ages
            payload['n_ranks'] = cluster['n_ranks']
            stale = sorted(r for r, a in ages.items()
                           if a is not None and a >= self.stale_after_s)
            if stale:
                payload['status'] = 'stale'
                payload['stale_ranks'] = stale
        if self.extra_health is not None:
            try:
                payload.update(self.extra_health() or {})
            except Exception as e:
                payload['extra_health_error'] = repr(e)
        return (200 if payload['status'] == 'ok' else 503), payload

    def diagnosis(self):
        from . import doctor
        return doctor.diagnose(events=events.events(),
                               snapshot=registry.snapshot(),
                               cluster=self._cluster())

    def costs(self):
        """The cost-explorer slice: ledger + aggregates + SLO burn."""
        from . import costs, slo
        return {'summary': costs.summary(), 'programs': costs.ledger(),
                'slo_burn': slo.burn_rates()}

    def timeseries(self, series=None):
        """The ring sampler's timelines: this process's live export plus
        the cluster merge when a run dir is attached. ``series``
        substring-filters the series maps (the full cluster map can be
        wide)."""
        from . import timeseries as ts
        live = ts.export_active()
        payload = {
            'live': live,
            'series': ts.to_series(live) if live else {},
        }
        if self.run_dir:
            from . import aggregate
            merged = aggregate.merged_timeseries(self.run_dir)
            if merged.get('series'):
                payload['cluster'] = merged
        if series:
            payload['series'] = {k: v for k, v in payload['series'].items()
                                 if series in k}
            if 'cluster' in payload:
                payload['cluster'] = dict(
                    payload['cluster'],
                    series={k: v
                            for k, v in payload['cluster']['series'].items()
                            if series in k})
        return payload

    # -- lifecycle -------------------------------------------------------
    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def start(self):
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self.host, self.port), _Handler)
        httpd.daemon_threads = True
        httpd.owner = self
        self.port = httpd.server_address[1]
        self._httpd = httpd
        self._sw = timing.Stopwatch()
        self._thread = threading.Thread(
            target=httpd.serve_forever, kwargs={'poll_interval': 0.25},
            name='paddle-tpu-telemetry-http', daemon=True)
        self._thread.start()
        events.emit('endpoint_start', url=self.url)
        return self

    def stop(self):
        httpd, self._httpd = self._httpd, None
        if httpd is None:
            return
        httpd.shutdown()
        httpd.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            from ..resilience.watchdog import join_thread
            join_thread(t, timeout=5.0)


def maybe_start_from_env(run_dir=None, extra_health=None):
    """Start the process-wide endpoint when telemetry is enabled and
    ``PADDLE_TPU_TELEMETRY_HTTP`` names a port; idempotent (the first
    caller wins; later callers may attach a run dir or health source to
    the running server). Returns the server or None."""
    if not state.enabled() or state.http_port() is None:
        return None
    with _lock:
        srv = _active[0]
        if srv is None:
            srv = MetricsServer(run_dir=run_dir or state.run_dir(),
                                extra_health=extra_health)
            try:
                srv.start()
            except OSError:
                return None   # port taken: another process exports already
            _active[0] = srv
        else:
            if run_dir and not srv.run_dir:
                srv.run_dir = run_dir
            if extra_health is not None and srv.extra_health is None:
                srv.extra_health = extra_health
        return srv


def active_server():
    return _active[0]


def detach_health(fn):
    """Drop ``fn`` as the active server's health source (no-op when a
    different source is attached). `==` not `is`: bound methods are a
    fresh object per attribute access. A stopped ServingEngine calls this
    so its dead worker/queues stop masquerading as this process's health
    — and so the next engine's start() can attach its own."""
    with _lock:
        srv = _active[0]
        if srv is not None and srv.extra_health == fn:
            srv.extra_health = None


def stop_active_server():
    with _lock:
        srv, _active[0] = _active[0], None
    if srv is not None:
        srv.stop()
