"""Step-event log: bounded in-memory buffer of structured events + JSONL.

Every noteworthy runtime occurrence — a training step, a NaN-guard skip, a
retry, a checkpoint commit — is one flat dict ``{'ev': kind, 'ts': wall
seconds, ...fields}``. Events accumulate in a bounded ring (newest win) and
are exported as JSONL by ``dump_jsonl()`` (the ``TelemetryCallback`` does
this at train end; ``tools/telemetry_dump.py`` pretty-prints / converts the
file). An optional live sink streams each event to disk as it is emitted —
for long runs where losing the tail on a crash matters more than the extra
write per event. Every emitted event is also mirrored into the flight
recorder's always-on ring (``flight.py``) so a crash dump carries the last
seconds even when no sink or flusher was configured.
"""
import collections
import json
import threading
import time

from . import flight, state

__all__ = ['emit', 'events', 'clear', 'dump_jsonl', 'set_sink',
           'close_sink', 'wall_ts', 'MAX_EVENTS']

MAX_EVENTS = 16384

_lock = threading.Lock()
_buf = collections.deque(maxlen=MAX_EVENTS)
_sink = None          # open file object, or None
_dropped = [0]


def wall_ts():
    """Wall-clock timestamp for event records (seconds since epoch). The one
    sanctioned raw-clock read for library code that needs a *timestamp*
    rather than a duration (durations go through ``observability.timer``)."""
    return time.time()


def emit(kind, **fields):
    """Record one event. No-op unless telemetry is enabled."""
    if not state.enabled():
        return None
    rec = {'ev': str(kind), 'ts': round(wall_ts(), 6)}
    rec.update(fields)
    # the flight recorder's ring mirrors every event so a crash dump
    # carries the last seconds even if no flusher ever fired
    flight.note(rec)
    with _lock:
        if len(_buf) == _buf.maxlen:
            _dropped[0] += 1
        _buf.append(rec)
        if _sink is not None:
            try:
                _sink.write(json.dumps(rec, sort_keys=True,
                                       default=_jsonable) + '\n')
                _sink.flush()
            except (OSError, ValueError):
                pass
    return rec


def events():
    """Snapshot of the buffered events, oldest first."""
    with _lock:
        return list(_buf)


def dropped():
    return _dropped[0]


def clear():
    with _lock:
        _buf.clear()
        _dropped[0] = 0


def dump_jsonl(path):
    """Write every buffered event to ``path`` as JSON-lines; returns the
    number of events written."""
    recs = events()
    with open(path, 'w', encoding='utf-8') as f:
        for rec in recs:
            f.write(json.dumps(rec, sort_keys=True, default=_jsonable) + '\n')
    return len(recs)


def set_sink(path):
    """Stream subsequent events live to ``path`` (append). Returns the path."""
    global _sink
    with _lock:
        if _sink is not None:
            _sink.close()
        _sink = open(path, 'a', encoding='utf-8')
    return path


def close_sink():
    global _sink
    with _lock:
        if _sink is not None:
            _sink.close()
            _sink = None


def _jsonable(o):
    """Last-resort encoder: numpy scalars -> python, everything else repr."""
    try:
        return o.item()
    except (AttributeError, ValueError):
        return repr(o)
