"""Flight recorder: an always-on black box for crash post-mortems.

A bounded ring buffer of the most recent runtime events that is on **even
when full telemetry is off** (one deque append under a lock per record —
negligible), dumped atomically to ``flight_rank<R>.json`` when the process
dies an abnormal death:

- NaN-abort (``resilience.NanStepError`` — eager and in-graph guards),
- a rank failure (``distributed.launch.RankFailedError``, supervisor side),
- a watchdog timeout (``resilience.watchdog.WatchdogTimeout``),
- SIGTERM (preemption — the signal handler installed by
  ``install_crash_hooks``),
- unhandled exceptions on the main thread (``sys.excepthook``) and worker
  threads (``threading.excepthook``).

While telemetry is enabled, every step event (``observability.event``) is
mirrored into the ring automatically; critical always-on sites call
``flight.record`` directly so the last seconds before a crash survive even
with the spine off. The dump is a single JSON document committed by
staged-write + ``os.replace`` — a reader never parses a torn file — and
carries the ring, a metrics snapshot, the interposed-counter summary, and
the cost-ledger summary. ``tools/postmortem.py`` renders a dump and runs
the anomaly doctor over it.

Env knobs (see also ``state.py``):

- ``PADDLE_TPU_FLIGHT=0``        disable the recorder entirely
- ``PADDLE_TPU_FLIGHT_EVENTS``   ring capacity (default 512 records)
- ``PADDLE_TPU_FLIGHT_DIR``      where dumps land (default: the cluster
                                 run dir when supervised, else the
                                 telemetry log dir)

Stdlib-only; imports only sibling observability modules (lazily where the
import could otherwise cycle).
"""
import collections
import json
import os
import signal
import socket
import sys
import threading
import time
import traceback

from . import state
from .state import _env_int

__all__ = ['record', 'note', 'records', 'dump', 'dump_path', 'enabled',
           'install_crash_hooks', 'uninstall_crash_hooks', 'clear',
           'load_dump', 'MAX_RECORDS']

MAX_RECORDS = max(_env_int('PADDLE_TPU_FLIGHT_EVENTS', 512), 1)

_DISABLED = os.environ.get('PADDLE_TPU_FLIGHT', '') == '0'
_lock = threading.Lock()
_ring = collections.deque(maxlen=MAX_RECORDS)
_dumps = [0]
_last_dump = [None]


def enabled():
    """The recorder rides along unless PADDLE_TPU_FLIGHT=0 — deliberately
    NOT gated on the telemetry switch (a black box that only records when
    someone remembered to turn it on records nothing useful)."""
    return not _DISABLED


def record(kind, **fields):
    """Append one record to the ring (always-on; bounded memory)."""
    if _DISABLED:
        return None
    # observability/ is GL011-exempt: the ring needs wall timestamps so a
    # post-mortem can be correlated with logs from other systems
    rec = {'ev': str(kind), 'ts': round(time.time(), 6)}
    rec.update(fields)
    with _lock:
        _ring.append(rec)
    return rec


def note(rec):
    """Mirror an already-built event record (the events.emit hook)."""
    if _DISABLED:
        return
    with _lock:
        _ring.append(dict(rec))


def records():
    """Snapshot of the ring, oldest first."""
    with _lock:
        return list(_ring)


def clear():
    with _lock:
        _ring.clear()
    _dumps[0] = 0
    _last_dump[0] = None


rank_id = state.rank_id


def _dump_dir(run_dir=None):
    return (run_dir or os.environ.get('PADDLE_TPU_FLIGHT_DIR')
            or state.run_dir() or state.log_dir())


def dump_path(run_dir=None, filename=None):
    return os.path.join(_dump_dir(run_dir),
                        filename or f'flight_rank{rank_id()}.json')


def dump(reason, exc=None, run_dir=None, extra=None, filename=None):
    """Atomically write the black box; returns the path or None.

    Best-effort by contract: a failed dump must never mask the crash that
    triggered it. Repeated dumps overwrite the same file — each dump first
    records itself into the ring, so the final document still names every
    earlier trigger. ``filename`` redirects writers that must NOT clobber
    this rank's primary black box (the supervisor's rank-failure record,
    the watchdog's rate-limited dumps).
    """
    if _DISABLED:
        return None
    doc = {
        'schema': 1,
        'reason': str(reason),
        'ts': round(time.time(), 6),
        'rank': rank_id(),
        'pid': os.getpid(),
        'host': socket.gethostname(),
        'telemetry_enabled': state.enabled(),
        'dumps_before': _dumps[0],
    }
    if exc is not None:
        doc['exception'] = {
            'type': type(exc).__name__,
            'message': str(exc),
            'traceback': ''.join(traceback.format_exception(
                type(exc), exc, exc.__traceback__)),
        }
    if extra:
        doc['extra'] = dict(extra)
    try:
        from . import costs, interpose, registry
        doc['metrics'] = registry.snapshot()
        doc['counters'] = interpose.summary()
        doc['costs'] = costs.summary()
    except Exception:
        pass   # a half-initialized process still gets its ring dumped
    doc['records'] = records()
    path = dump_path(run_dir, filename=filename)
    try:
        os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, 'w', encoding='utf-8') as f:   # atomic-ok: staged,
            f.write(json.dumps(doc, sort_keys=True,   # fsynced, then
                               default=repr))         # os.replace'd below
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        return None
    _dumps[0] += 1
    _last_dump[0] = path
    record('flight.dump', reason=str(reason), path=path)
    return path


def last_dump():
    return _last_dump[0]


def load_dump(path):
    """Parse a flight dump; None when the file is absent or torn (a
    partial write never parses — the atomic commit makes this the ONLY
    two outcomes)."""
    try:
        with open(path, 'r', encoding='utf-8') as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) and 'reason' in doc else None


# -- crash hooks -------------------------------------------------------------

_hooks = {'installed': False, 'sigterm': None, 'excepthook': None,
          'threading': None}


def install_crash_hooks():
    """Install the SIGTERM / sys.excepthook / threading.excepthook dump
    triggers (idempotent; previous handlers are chained, not replaced).
    The SIGTERM handler can only be installed from the main thread — the
    other two hooks still install elsewhere. Returns True when (already)
    installed."""
    if _DISABLED:
        return False
    if _hooks['installed']:
        return True

    prev_except = sys.excepthook

    def _excepthook(tp, val, tb):
        try:
            dump('unhandled_exception', exc=val)
        except Exception:
            pass
        prev_except(tp, val, tb)

    sys.excepthook = _excepthook
    _hooks['excepthook'] = prev_except

    prev_thread = threading.excepthook

    def _threadhook(args):
        try:
            dump('worker_exception', exc=args.exc_value,
                 extra={'thread': getattr(args.thread, 'name', None)})
        except Exception:
            pass
        prev_thread(args)

    threading.excepthook = _threadhook
    _hooks['threading'] = prev_thread

    try:
        prev_sig = signal.getsignal(signal.SIGTERM)

        def _on_sigterm(signum, frame):
            try:
                dump('sigterm')
            except Exception:
                pass
            if callable(prev_sig):
                prev_sig(signum, frame)
            else:
                # restore the previous disposition and re-deliver so the
                # process still dies with the default SIGTERM semantics
                signal.signal(signal.SIGTERM,
                              prev_sig if prev_sig is not None
                              else signal.SIG_DFL)
                signal.raise_signal(signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_sigterm)
        _hooks['sigterm'] = prev_sig
    except (ValueError, OSError, TypeError):
        pass   # not the main thread (or an embedded interpreter)
    _hooks['installed'] = True
    record('flight.hooks_installed')
    return True


def uninstall_crash_hooks():
    """Restore the chained handlers (test isolation)."""
    if not _hooks['installed']:
        return
    if _hooks['excepthook'] is not None:
        sys.excepthook = _hooks['excepthook']
        _hooks['excepthook'] = None
    if _hooks['threading'] is not None:
        threading.excepthook = _hooks['threading']
        _hooks['threading'] = None
    if _hooks['sigterm'] is not None:
        try:
            signal.signal(signal.SIGTERM, _hooks['sigterm'])
        except (ValueError, OSError, TypeError):
            pass
        _hooks['sigterm'] = None
    _hooks['installed'] = False
