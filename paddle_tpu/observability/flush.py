"""Per-rank telemetry flusher: the rank side of mission control.

Each supervised rank (``distributed.launch`` spawn workers, launch-CLI
scripts, or any process with ``PADDLE_TPU_TELEMETRY_RUN_DIR`` set) runs one
``RankFlusher``: a daemon thread that every ``flush_every`` seconds writes
the process's telemetry — metrics snapshot + interposed-counter summary,
the step-event buffer, and the span buffer — to per-rank files in the
supervisor's run dir:

- ``telemetry_rank<R>.json``   {rank, pid, host, ts, metrics, counters}
- ``events_rank<R>.jsonl``     the JSONL event log (rank-stamped)
- ``trace_rank<R>.json``       Chrome trace events for this rank
- ``timeseries_rank<R>.json``  the ring sampler's delta-encoded time
                               series (written only once samples exist)

The supervisor-side ``aggregate`` module merges these into one cluster
snapshot and a single Perfetto trace with one lane per rank. Files are
staged-then-renamed so a reader (the aggregator polls while ranks run)
never sees a torn JSON document; events are appended-rewritten from the
bounded in-memory buffer, so a crashed rank leaves its last flush behind —
that tail is exactly what the doctor needs.

Stdlib-only; never imports jax or other paddle_tpu packages.
"""
import json
import os
import socket
import threading

from . import costs, events, interpose, registry, spans, state, timeseries
from .state import rank_id

__all__ = ['RankFlusher', 'start_rank_flusher', 'stop_rank_flusher',
           'active_flusher', 'rank_id']

_lock = threading.Lock()
_active = [None]


class RankFlusher:
    """Periodically export this process's telemetry to per-rank files.

    ``flush_now()`` is safe to call from any thread at any time (the last
    writer wins — each file is a complete document, committed by rename).
    """

    def __init__(self, run_dir, rank=None, interval=None):
        self.run_dir = os.fspath(run_dir)
        self.rank = rank_id() if rank is None else int(rank)
        self.interval = (state.flush_every() if interval is None
                         else float(interval))
        self.host = socket.gethostname()
        self._stop = threading.Event()
        self._thread = None
        # flush_now() is public and the flusher thread calls it too: the
        # lock serializes whole flushes (two writers in one process would
        # collide on the same pid-suffixed staging file) and guards the
        # flushes counter
        self._flush_lock = threading.Lock()
        self.flushes = 0

    # -- file layout (shared with aggregate.py) -------------------------
    @property
    def metrics_path(self):
        return os.path.join(self.run_dir, f'telemetry_rank{self.rank}.json')

    @property
    def events_path(self):
        return os.path.join(self.run_dir, f'events_rank{self.rank}.jsonl')

    @property
    def trace_path(self):
        return os.path.join(self.run_dir, f'trace_rank{self.rank}.json')

    @property
    def timeseries_path(self):
        return os.path.join(self.run_dir,
                            f'timeseries_rank{self.rank}.json')

    def _commit(self, path, text):
        """Whole-document write, committed by rename so the aggregator's
        concurrent read never sees a torn file."""
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, 'w', encoding='utf-8') as f:   # atomic-ok: staged
            f.write(text)                             # then os.replace'd
        os.replace(tmp, path)

    def flush_now(self):
        """Write all three per-rank files from the current buffers."""
        with self._flush_lock:
            os.makedirs(self.run_dir, exist_ok=True)
            head = {
                'rank': self.rank,
                'pid': os.getpid(),
                'host': self.host,
                'ts': round(events.wall_ts(), 6),
                'metrics': registry.snapshot(),
                'counters': interpose.summary(),
                'costs': costs.summary(),
            }
            try:
                self._commit(self.metrics_path,
                             json.dumps(head, sort_keys=True, default=repr))
                evs = events.events()
                self._commit(self.events_path, ''.join(
                    json.dumps(dict(rec, rank=self.rank), sort_keys=True,
                               default=repr) + '\n' for rec in evs))
                self._commit(self.trace_path,
                             json.dumps(spans.trace_events()))
                ts_doc = timeseries.export_active()
                if ts_doc is not None:
                    ts_doc['rank'] = self.rank
                    self._commit(self.timeseries_path,
                                 json.dumps(ts_doc, sort_keys=True))
            except OSError:
                return False  # run dir vanished (supervisor cleanup): benign
            self.flushes += 1
            return True

    def _run(self):
        while not self._stop.wait(self.interval):
            if state.enabled():
                self.flush_now()

    def start(self):
        if self._thread is None:
            if state.enabled():
                self.flush_now()
            self._thread = threading.Thread(
                target=self._run, name='paddle-tpu-telemetry-flush',
                daemon=True)
            self._thread.start()
        return self

    def stop(self, final_flush=True):
        self._stop.set()
        t = self._thread
        if t is not None:
            from ..resilience.watchdog import join_thread
            join_thread(t, timeout=max(self.interval * 4, 2.0))
            self._thread = None
        if final_flush and state.enabled():
            self.flush_now()


def start_rank_flusher(run_dir=None, rank=None):
    """Start (or return) the process-wide flusher. ``run_dir`` defaults to
    the cluster run dir from the environment; returns None when there is
    none (not a cluster run) or telemetry is disabled."""
    if not state.enabled():
        return None
    run_dir = run_dir or state.run_dir()
    if not run_dir:
        return None
    with _lock:
        fl = _active[0]
        if fl is not None and fl.run_dir == os.fspath(run_dir):
            return fl
        if fl is not None:
            fl.stop(final_flush=False)
        fl = RankFlusher(run_dir, rank=rank).start()
        _active[0] = fl
    # the time-series ring rides the flusher: every supervised rank samples
    # at cadence so the aggregator gets timelines, not just the last frame
    timeseries.start_sampler()
    return fl


def stop_rank_flusher(final_flush=True):
    with _lock:
        fl, _active[0] = _active[0], None
    if fl is not None:
        # take one last sample so the final flush carries the run's tail,
        # then park the cadence thread (the ring keeps its samples)
        sm = timeseries.active_sampler()
        if sm is not None and final_flush:
            sm.sample_now()
        timeseries.stop_sampler()
        fl.stop(final_flush=final_flush)


def active_flusher():
    return _active[0]
