"""Interposed runtime counters: jit retraces/compiles and host transfers.

Two families of counters no library author has to remember to bump:

- **retrace/compile**: ``install_jax_hooks()`` registers a
  ``jax.monitoring`` duration listener; every jaxpr trace and every backend
  compile anywhere in the process (Executor programs, hapi jit steps, bench
  loops, user code) increments ``jax.traces`` / ``jax.compiles`` and
  accumulates ``jax.compile_ms``. A growing ``jax.traces`` count on a
  steady-state loop is the retrace-storm signal GL004–GL006 lint for
  statically.
- **host transfers**: the narrow host-boundary waists (``Tensor.numpy()``,
  ``Executor.run``'s fetch) call ``record_host_transfer(nbytes)``; the
  ``host_transfer.bytes`` counter is the "how much crosses PCIe/ICI per
  step" number the ROADMAP's serving goal needs.

Collectives report through ``record_collective(op, nbytes)`` from the eager
wrappers (inside a traced region the record happens once at trace time, so
counts there reflect compilations, not executions).
"""
from . import registry, state

__all__ = ['install_jax_hooks', 'record_host_transfer', 'record_collective',
           'summary']

_installed = [False]


def install_jax_hooks():
    """Register the jax.monitoring listener once. Safe to call repeatedly;
    returns True when the hooks are (already) in place. The listener guards
    on ``state.enabled()`` so a later ``disable()`` silences it without an
    unregister API."""
    if _installed[0]:
        return True
    try:
        import jax
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        return False
    _installed[0] = True
    return True


def _on_duration(name, secs, **kwargs):
    if not state.enabled():
        return
    if name.endswith('jaxpr_trace_duration'):
        registry.counter('jax.traces').inc()
        registry.histogram('jax.trace_ms').observe(secs * 1e3)
    elif name.endswith('backend_compile_duration'):
        registry.counter('jax.compiles').inc()
        registry.counter('jax.compile_ms').inc(secs * 1e3)
        registry.histogram('jax.compile_duration_ms').observe(secs * 1e3)


def record_host_transfer(nbytes, kind='device_get'):
    """Count one device→host materialization of ``nbytes`` bytes."""
    if not state.enabled():
        return
    registry.counter('host_transfer.calls').inc()
    registry.counter('host_transfer.bytes').inc(int(nbytes))
    registry.counter(f'host_transfer.{kind}.bytes').inc(int(nbytes))


def record_collective(op, nbytes):
    """Count one collective launch of ``nbytes`` payload bytes."""
    if not state.enabled():
        return
    registry.counter(f'collective.{op}.calls').inc()
    registry.counter(f'collective.{op}.bytes').inc(int(nbytes))


def summary():
    """The headline interposed counters, for bench extras / train_end
    events: retraces (jaxpr traces), compiles, total compile ms,
    host-transfer traffic, and the fault-tolerance tallies (worker
    restarts, quarantined samples, watchdog/collective timeouts, rank
    failures/restarts) — a run that self-healed is not the same run as one
    that never faulted, and the record should say so."""
    snap = registry.snapshot()['counters']
    return {
        'jax_traces': snap.get('jax.traces', 0),
        'jax_compiles': snap.get('jax.compiles', 0),
        'jax_compile_ms': round(float(snap.get('jax.compile_ms', 0)), 3),
        'host_transfer_bytes': snap.get('host_transfer.bytes', 0),
        'host_transfer_calls': snap.get('host_transfer.calls', 0),
        'engine_steps': snap.get('engine.steps', 0),
        'engine_loss_fetch_bytes': snap.get(
            'host_transfer.engine.loss_fetch.bytes', 0),
        'worker_restarts': snap.get('dataloader.worker_restarts', 0),
        'quarantined_samples': snap.get('dataloader.quarantined', 0),
        'watchdog_timeouts': snap.get('dataloader.watchdog_timeouts', 0),
        'dist_timeouts': snap.get('distributed.timeouts', 0),
        'rank_failures': snap.get('distributed.rank_failures', 0),
        'rank_restarts': snap.get('distributed.rank_restarts', 0),
        'serving_requests': snap.get('serving.requests', 0),
        'serving_shed': snap.get('serving.shed', 0),
        'serving_shed_queue_full': snap.get('serving.shed.queue_full', 0),
        'serving_shed_page_exhaustion': snap.get(
            'serving.shed.page_exhaustion', 0),
        'serving_deadline_expired': snap.get('serving.deadline_expired', 0),
        'serving_kv_decode_stalls': snap.get('serving.kv.decode_stalls', 0),
        'serving_kv_prefill_stalls': snap.get(
            'serving.kv.prefill_stalls', 0),
        'serving_preemptions': snap.get('serving.preemptions', 0),
        'serving_prefix_hit_pages': snap.get(
            'serving.kv.prefix_hit_pages', 0),
        'serving_spec_proposed': snap.get('serving.spec.proposed', 0),
        'serving_spec_accepted': snap.get('serving.spec.accepted', 0),
        'cost_programs': snap.get('cost.programs', 0),
        'cost_captures': snap.get('cost.captures', 0),
        'slo_requests': snap.get('slo.requests_total', 0),
        'slo_violations': snap.get('slo.violations_total', 0),
    }
