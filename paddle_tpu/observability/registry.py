"""Process-wide metrics registry: counters, gauges, bounded histograms.

One thread-safe singleton (``get_registry()``) shared by every instrumented
layer; exporters read a consistent ``snapshot()`` or the Prometheus-style
text exposition (``to_prometheus()``). All instruments are created lazily by
name — ``counter('executor.program_cache.misses').inc()`` is the whole API
at a call site — so instrumentation never needs registration boilerplate.

Updates are metric-local locks (an ``inc()`` never contends with an
unrelated ``observe()``); creation takes the registry lock once per name.
"""
import math
import random
import re
import threading

__all__ = ['Counter', 'Gauge', 'Histogram', 'MetricsRegistry',
           'get_registry', 'counter', 'gauge', 'histogram',
           'reset', 'snapshot', 'to_prometheus']


class Counter:
    """Monotonically increasing value (int or float increments)."""

    kind = 'counter'

    def __init__(self, name):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self):
        return self._value


class Gauge:
    """Last-write-wins instantaneous value (queue depth, cache size, ...)."""

    kind = 'gauge'

    def __init__(self, name):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value


class Histogram:
    """Streaming distribution with a bounded reservoir.

    Exact count/sum/min/max plus a ``reservoir_size``-bounded uniform sample
    (Vitter's algorithm R, deterministic per-instrument seed) for quantile
    estimates — memory stays O(reservoir) over arbitrarily long runs.
    """

    kind = 'histogram'

    def __init__(self, name, reservoir_size=512):
        self.name = name
        self.reservoir_size = int(reservoir_size)
        self._lock = threading.Lock()
        self._rng = random.Random(hash(name) & 0xffffffff)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._reservoir = []

    def observe(self, x):
        x = float(x)
        with self._lock:
            self.count += 1
            self.sum += x
            self.min = min(self.min, x)
            self.max = max(self.max, x)
            if len(self._reservoir) < self.reservoir_size:
                self._reservoir.append(x)
            else:
                j = self._rng.randrange(self.count)
                if j < self.reservoir_size:
                    self._reservoir[j] = x

    @property
    def mean(self):
        return self.sum / self.count if self.count else 0.0

    def percentile(self, p):
        """Estimated p-th percentile (0..100) from the reservoir."""
        with self._lock:
            if not self._reservoir:
                return 0.0
            vals = sorted(self._reservoir)
        k = min(len(vals) - 1, max(0, int(round(p / 100.0 * (len(vals) - 1)))))
        return vals[k]

    def stats(self):
        with self._lock:
            if not self.count:
                return {'count': 0, 'sum': 0.0, 'min': 0.0, 'max': 0.0,
                        'mean': 0.0, 'p50': 0.0, 'p99': 0.0}
        return {'count': self.count, 'sum': self.sum, 'min': self.min,
                'max': self.max, 'mean': self.mean,
                'p50': self.percentile(50), 'p99': self.percentile(99)}


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _get(self, cls, name, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested as {cls.kind}")
            return m

    def counter(self, name):
        return self._get(Counter, name)

    def gauge(self, name):
        return self._get(Gauge, name)

    def histogram(self, name, reservoir_size=512):
        return self._get(Histogram, name, reservoir_size=reservoir_size)

    def reset(self):
        with self._lock:
            self._metrics.clear()

    def snapshot(self):
        """Consistent point-in-time dict: counters/gauges as scalars,
        histograms as their stats dicts."""
        with self._lock:
            items = list(self._metrics.items())
        out = {'counters': {}, 'gauges': {}, 'histograms': {}}
        for name, m in sorted(items):
            if m.kind == 'counter':
                out['counters'][name] = m.value
            elif m.kind == 'gauge':
                out['gauges'][name] = m.value
            else:
                out['histograms'][name] = m.stats()
        return out

    def to_prometheus(self, prefix='paddle_tpu'):
        """Prometheus-style text exposition (metric names sanitized to
        ``[a-z0-9_]``; histograms exposed summary-style)."""
        lines = []
        snap = self.snapshot()
        for name, v in snap['counters'].items():
            n = _sanitize(prefix, name)
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {_fmt(v)}")
        for name, v in snap['gauges'].items():
            n = _sanitize(prefix, name)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {_fmt(v)}")
        for name, st in snap['histograms'].items():
            n = _sanitize(prefix, name)
            lines.append(f"# TYPE {n} summary")
            lines.append(f"{n}_count {st['count']}")
            lines.append(f"{n}_sum {_fmt(st['sum'])}")
            for q, key in (('0.5', 'p50'), ('0.99', 'p99')):
                lines.append(f'{n}{{quantile="{q}"}} {_fmt(st[key])}')
        return '\n'.join(lines) + ('\n' if lines else '')


def _sanitize(prefix, name):
    return re.sub(r'[^a-zA-Z0-9_]', '_', f"{prefix}_{name}").lower()


def _fmt(v):
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


_REGISTRY = MetricsRegistry()


def get_registry():
    return _REGISTRY


def counter(name):
    return _REGISTRY.counter(name)


def gauge(name):
    return _REGISTRY.gauge(name)


def histogram(name, reservoir_size=512):
    return _REGISTRY.histogram(name, reservoir_size=reservoir_size)


def reset():
    _REGISTRY.reset()


def snapshot():
    return _REGISTRY.snapshot()


def to_prometheus(prefix='paddle_tpu'):
    return _REGISTRY.to_prometheus(prefix=prefix)
