"""Process-wide metrics registry: counters, gauges, bounded histograms.

One thread-safe singleton (``get_registry()``) shared by every instrumented
layer; exporters read a consistent ``snapshot()`` or the Prometheus-style
text exposition (``to_prometheus()``). All instruments are created lazily by
name — ``counter('executor.program_cache.misses').inc()`` is the whole API
at a call site — so instrumentation never needs registration boilerplate.

Instruments may carry **labels** (``counter('cluster.step_ms', labels=
{'rank': '3'})``): one metric family, many label sets — the shape the
cross-rank aggregator and the Prometheus exposition need for per-rank
series. A family's label *keys* are pinned by its first use; re-creating
the same name with a different key set raises (two meanings under one
exposition name would silently merge in a scrape).

Updates are metric-local locks (an ``inc()`` never contends with an
unrelated ``observe()``); creation takes the registry lock once per name.
"""
import math
import random
import re
import threading

__all__ = ['Counter', 'Gauge', 'Histogram', 'MetricsRegistry',
           'get_registry', 'counter', 'gauge', 'histogram',
           'reset', 'snapshot', 'to_prometheus', 'escape_label_value']


def _norm_labels(labels):
    """Validated ``{str: str}`` copy of a labels mapping (or None)."""
    if not labels:
        return None
    out = {}
    for k, v in labels.items():
        k = str(k)
        if not re.match(r'^[a-zA-Z_][a-zA-Z0-9_]*$', k):
            raise ValueError(f"invalid metric label name {k!r}")
        out[k] = str(v)
    return out


def _labels_key(labels):
    """Canonical instrument-key suffix for a label set ('' when unlabeled).
    json keeps values with commas/quotes unambiguous."""
    if not labels:
        return ''
    import json
    return json.dumps(labels, sort_keys=True, separators=(',', ':'))


class Counter:
    """Monotonically increasing value (int or float increments)."""

    kind = 'counter'

    def __init__(self, name, labels=None):
        self.name = name
        self.labels = _norm_labels(labels)
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n
            return self._value

    @property
    def value(self):
        return self._value


class Gauge:
    """Last-write-wins instantaneous value (queue depth, cache size, ...)."""

    kind = 'gauge'

    def __init__(self, name, labels=None):
        self.name = name
        self.labels = _norm_labels(labels)
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v):
        with self._lock:
            self._value = v

    def inc(self, n=1):
        with self._lock:
            self._value += n

    def dec(self, n=1):
        with self._lock:
            self._value -= n

    @property
    def value(self):
        return self._value


class Histogram:
    """Streaming distribution with a bounded reservoir.

    Exact count/sum/min/max plus a ``reservoir_size``-bounded uniform sample
    (Vitter's algorithm R, deterministic per-instrument seed) for quantile
    estimates — memory stays O(reservoir) over arbitrarily long runs.
    """

    kind = 'histogram'

    def __init__(self, name, reservoir_size=512, labels=None):
        self.name = name
        self.labels = _norm_labels(labels)
        self.reservoir_size = int(reservoir_size)
        self._lock = threading.Lock()
        self._rng = random.Random(hash(name) & 0xffffffff)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._reservoir = []

    def observe(self, x):
        x = float(x)
        with self._lock:
            self.count += 1
            self.sum += x
            self.min = min(self.min, x)
            self.max = max(self.max, x)
            if len(self._reservoir) < self.reservoir_size:
                self._reservoir.append(x)
            else:
                j = self._rng.randrange(self.count)
                if j < self.reservoir_size:
                    self._reservoir[j] = x

    @property
    def mean(self):
        return self.sum / self.count if self.count else 0.0

    def percentile(self, p):
        """Estimated p-th percentile (0..100) from the reservoir."""
        with self._lock:
            if not self._reservoir:
                return 0.0
            vals = sorted(self._reservoir)
        k = min(len(vals) - 1, max(0, int(round(p / 100.0 * (len(vals) - 1)))))
        return vals[k]

    def stats(self):
        with self._lock:
            if not self.count:
                return {'count': 0, 'sum': 0.0, 'min': 0.0, 'max': 0.0,
                        'mean': 0.0, 'p50': 0.0, 'p99': 0.0}
        return {'count': self.count, 'sum': self.sum, 'min': self.min,
                'max': self.max, 'mean': self.mean,
                'p50': self.percentile(50), 'p99': self.percentile(99)}


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}        # (name, labels_key) -> instrument
        self._label_keys = {}     # name -> frozenset of label key names
        self._kinds = {}          # name -> instrument class (one per family)

    def _get(self, cls, name, labels=None, **kwargs):
        labels = _norm_labels(labels)
        key = (name, _labels_key(labels))
        keyset = frozenset(labels) if labels else frozenset()
        with self._lock:
            # kind is pinned per FAMILY, not per (name, labels) — a
            # counter('x', m=a) followed by gauge('x', m=b) would otherwise
            # be created fine and then poison every to_prometheus() call
            pinned_cls = self._kinds.get(name)
            if pinned_cls is not None and pinned_cls is not cls:
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{pinned_cls.kind}, requested as {cls.kind}")
            m = self._metrics.get(key)
            if m is None:
                pinned = self._label_keys.get(name)
                if pinned is not None and pinned != keyset:
                    raise ValueError(
                        f"metric {name!r} already registered with label set "
                        f"{sorted(pinned) or '(none)'}, requested with "
                        f"{sorted(keyset) or '(none)'} — one family, one "
                        "label key set (a scrape would merge two meanings "
                        "under one exposition name)")
                m = cls(name, labels=labels, **kwargs)
                self._metrics[key] = m
                self._label_keys.setdefault(name, keyset)
                self._kinds.setdefault(name, cls)
            return m

    def counter(self, name, labels=None):
        return self._get(Counter, name, labels=labels)

    def gauge(self, name, labels=None):
        return self._get(Gauge, name, labels=labels)

    def histogram(self, name, reservoir_size=512, labels=None):
        return self._get(Histogram, name, labels=labels,
                         reservoir_size=reservoir_size)

    def reset(self):
        with self._lock:
            self._metrics.clear()
            self._label_keys.clear()
            self._kinds.clear()

    def _sorted_instruments(self):
        with self._lock:
            items = list(self._metrics.items())
        return [m for _, m in sorted(items, key=lambda kv: kv[0])]

    def snapshot(self):
        """Consistent point-in-time dict: counters/gauges as scalars,
        histograms as their stats dicts. Labeled instruments appear under
        ``name{k=v,...}`` keys (sorted label order)."""
        out = {'counters': {}, 'gauges': {}, 'histograms': {}}
        for m in self._sorted_instruments():
            key = m.name if not m.labels else m.name + '{' + ','.join(
                f"{k}={v}" for k, v in sorted(m.labels.items())) + '}'
            if m.kind == 'counter':
                out['counters'][key] = m.value
            elif m.kind == 'gauge':
                out['gauges'][key] = m.value
            else:
                out['histograms'][key] = m.stats()
        return out

    def to_prometheus(self, prefix='paddle_tpu'):
        """Prometheus-style text exposition.

        Metric names are sanitized to ``[a-z0-9_]``; label values are
        escaped per the exposition format (backslash, double-quote, and
        newline); histograms are exposed summary-style. Two distinct
        metric families that sanitize to the SAME exposition name (e.g. a
        serving counter and a dataloader counter differing only in
        punctuation) raise instead of silently merging their series."""
        by_name = {}    # exposition name -> (raw name, kind, [instruments])
        for m in self._sorted_instruments():
            n = _sanitize(prefix, m.name)
            entry = by_name.get(n)
            if entry is None:
                by_name[n] = (m.name, m.kind, [m])
            elif entry[0] != m.name or entry[1] != m.kind:
                raise ValueError(
                    f"metric-name collision in Prometheus exposition: "
                    f"{entry[0]!r} ({entry[1]}) and {m.name!r} ({m.kind}) "
                    f"both sanitize to {n!r} — rename one family")
            else:
                entry[2].append(m)
        lines = []
        for n, (_raw, kind, instruments) in by_name.items():
            if kind == 'histogram':
                lines.append(f"# TYPE {n} summary")
                for m in instruments:
                    st = m.stats()
                    lbl = _render_labels(m.labels)
                    lines.append(f"{n}_count{lbl} {st['count']}")
                    lines.append(f"{n}_sum{lbl} {_fmt(st['sum'])}")
                    for q, key in (('0.5', 'p50'), ('0.99', 'p99')):
                        qlbl = _render_labels(dict(m.labels or {},
                                                   quantile=q))
                        lines.append(f"{n}{qlbl} {_fmt(st[key])}")
            else:
                lines.append(f"# TYPE {n} {kind}")
                for m in instruments:
                    lines.append(
                        f"{n}{_render_labels(m.labels)} {_fmt(m.value)}")
        return '\n'.join(lines) + ('\n' if lines else '')


def _sanitize(prefix, name):
    return re.sub(r'[^a-zA-Z0-9_]', '_', f"{prefix}_{name}").lower()


def escape_label_value(v):
    """Escape one label value per the Prometheus text exposition format:
    backslash, double-quote, and line feed."""
    return (str(v).replace('\\', '\\\\').replace('"', '\\"')
            .replace('\n', '\\n'))


def _render_labels(labels):
    if not labels:
        return ''
    inner = ','.join(f'{k}="{escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return '{' + inner + '}'


def _fmt(v):
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


_REGISTRY = MetricsRegistry()


def get_registry():
    return _REGISTRY


def counter(name, labels=None):
    return _REGISTRY.counter(name, labels=labels)


def gauge(name, labels=None):
    return _REGISTRY.gauge(name, labels=labels)


def histogram(name, reservoir_size=512, labels=None):
    return _REGISTRY.histogram(name, reservoir_size=reservoir_size,
                               labels=labels)


def reset():
    _REGISTRY.reset()


def snapshot():
    return _REGISTRY.snapshot()


def to_prometheus(prefix='paddle_tpu'):
    return _REGISTRY.to_prometheus(prefix=prefix)
