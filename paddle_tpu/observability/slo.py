"""SLO tracker: per-model latency objectives + error-budget burn.

A serving model registered with a latency objective (``ServingEngine.
register(..., slo_ms=50, slo_objective=0.99)``) gets every completed
request judged against it: a request **violates** when it errored, missed
its deadline, or took longer than ``slo_ms`` end-to-end. The tracker keeps
always-on tallies (the ``_Stats`` discipline — plain dict math, telemetry
mirrors it when enabled) and computes the **error-budget burn rate**::

    burn = (violations / requests) / (1 - objective)

``burn == 1`` means the model is spending its budget exactly as fast as
the objective allows; ``burn > 1`` is an SLO on fire — the doctor's
``slo_burn`` detector names it (warning at 1x, critical at 5x).

Telemetry surface (while enabled): ``slo.requests{model=}`` /
``slo.violations{model=}`` counters, ``slo.burn_rate{model=}`` gauge,
unlabeled ``slo.requests_total`` / ``slo.violations_total`` for the
interposed-counter summary, and one ``slo.violation`` event per bad
request (the evidence trail the doctor and ``tools/doctor.py`` read).

``PADDLE_TPU_SLO_MS`` (+ optional ``PADDLE_TPU_SLO_OBJECTIVE``, default
0.99) sets a process-wide default objective for models without an explicit
one. Stdlib-only.
"""
import os
import threading

from . import events, registry, state

__all__ = ['set_objective', 'clear_objective', 'objective', 'objectives',
           'record', 'burn_rates', 'tallies', 'reset']

DEFAULT_OBJECTIVE = 0.99

_lock = threading.Lock()
_objectives = {}     # model -> {'target_ms': float, 'objective': float}
_tallies = {}        # model -> {'requests': int, 'violations': int}


def _env_default():
    raw = os.environ.get('PADDLE_TPU_SLO_MS', '')
    if not raw:
        return None
    try:
        target = float(raw)
    except ValueError:
        return None
    try:
        obj = float(os.environ.get('PADDLE_TPU_SLO_OBJECTIVE', '')
                    or DEFAULT_OBJECTIVE)
    except ValueError:
        obj = DEFAULT_OBJECTIVE
    return {'target_ms': target, 'objective': obj}


def set_objective(model, target_ms, objective=DEFAULT_OBJECTIVE):
    """Declare the latency SLO for ``model``: ``objective`` of requests
    must complete OK within ``target_ms``."""
    target_ms = float(target_ms)
    objective = float(objective)
    if target_ms <= 0:
        raise ValueError(f"slo: target_ms must be > 0, got {target_ms}")
    if not 0.0 < objective < 1.0:
        raise ValueError(
            f"slo: objective must be in (0, 1), got {objective} "
            "(0.99 == 99% of requests within target)")
    with _lock:
        _objectives[model] = {'target_ms': target_ms,
                              'objective': objective}
    return _objectives[model]


def clear_objective(model):
    with _lock:
        _objectives.pop(model, None)
        _tallies.pop(model, None)


def objective(model):
    """The model's objective dict, the env default, or None (untracked)."""
    with _lock:
        obj = _objectives.get(model)
    return obj or _env_default()


def objectives():
    with _lock:
        out = {m: dict(o) for m, o in _objectives.items()}
    env = _env_default()
    if env:
        out.setdefault('*', env)
    return out


def record(model, status, latency_ms):
    """Judge one completed request against the model's objective. Returns
    the updated burn rate, or None when the model has no objective.
    Always-on tallies; telemetry mirrored only while enabled."""
    obj = objective(model)
    if obj is None:
        return None
    violated = status != 'ok' or float(latency_ms) > obj['target_ms']
    with _lock:
        t = _tallies.setdefault(model, {'requests': 0, 'violations': 0})
        t['requests'] += 1
        if violated:
            t['violations'] += 1
        requests, violations = t['requests'], t['violations']
    budget = max(1.0 - obj['objective'], 1e-9)
    burn = (violations / requests) / budget
    if state.enabled():
        lbl = {'model': str(model)}
        registry.counter('slo.requests', labels=lbl).inc()
        registry.counter('slo.requests_total').inc()
        registry.gauge('slo.burn_rate', labels=lbl).set(round(burn, 4))
        if violated:
            registry.counter('slo.violations', labels=lbl).inc()
            registry.counter('slo.violations_total').inc()
            events.emit('slo.violation', model=str(model), status=status,
                        latency_ms=round(float(latency_ms), 3),
                        target_ms=obj['target_ms'],
                        objective=obj['objective'],
                        burn_rate=round(burn, 4))
    return burn


def burn_rates():
    """{model: burn} for every tracked model with traffic."""
    out = {}
    with _lock:
        items = [(m, dict(t)) for m, t in _tallies.items()]
    for model, t in items:
        obj = objective(model)
        if obj is None or not t['requests']:
            continue
        budget = max(1.0 - obj['objective'], 1e-9)
        out[model] = round((t['violations'] / t['requests']) / budget, 4)
    return out


def tallies():
    with _lock:
        return {m: dict(t) for m, t in _tallies.items()}


def reset():
    with _lock:
        _objectives.clear()
        _tallies.clear()
