"""Span tracer: Chrome trace-event JSON + jax.profiler bridge.

``span(name)`` times a host-side region and records a Chrome trace "complete"
event (``ph: "X"``, microsecond ``ts``/``dur``) into a bounded in-process
buffer; ``dump_chrome_trace(path)`` writes the buffer as a JSON array that
loads directly in Perfetto / chrome://tracing.

Besides synchronous spans, the buffer carries **async (flow) events** —
``async_begin``/``async_instant``/``async_end`` record nestable Chrome
async events (``ph: b/n/e``) sharing a ``cat`` + ``id`` pair, which
Perfetto renders as ONE connected lane spanning threads and time. The
serving engine threads each request's id through them so a request's
lifecycle (admitted → prefill chunks → decode iterations → speculative
verify → completion) reads as a single flow in the merged cluster trace
(docs/OBSERVABILITY.md, "Per-request traces").

Two disciplines keep the tracer honest on an async accelerator runtime:

- **device-trace bridging**: while a ``jax.profiler`` trace is active
  (``utils.profiler.start_profiler``), every span also enters a
  ``jax.profiler.TraceAnnotation`` so the same region shows up in the xplane
  dump — one set of annotations, two viewers.
- **sampled sync**: a span wrapping dispatched device work measures only
  host dispatch time unless it blocks. ``span(name, sync=value)`` calls
  ``jax.block_until_ready(value)`` on a *sampled* subset of occurrences (the
  1st and every ``PADDLE_TPU_TELEMETRY_SYNC_EVERY``-th per span name, default
  16) so timing never adds an unsampled host sync to the steady-state step.
  Synced occurrences carry ``args.synced: true`` so readers can tell real
  latencies from dispatch times.
"""
import json
import os
import threading
import time

from . import state

__all__ = ['span', 'Span', 'dump_chrome_trace', 'trace_events',
           'async_begin', 'async_instant', 'async_end',
           'clear', 'MAX_TRACE_EVENTS']

MAX_TRACE_EVENTS = 65536

_lock = threading.Lock()
_events = []
_dropped = [0]
_sync_counts = {}
_EPOCH = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _EPOCH) * 1e6


def _device_trace_active():
    """True while utils.profiler has a jax device trace running."""
    try:
        from ..utils import profiler
        return profiler._active.get('dir') is not None
    except Exception:
        return False


def _should_sync(name):
    every = state.sync_every()
    if every <= 0:
        return False
    with _lock:
        n = _sync_counts.get(name, 0)
        _sync_counts[name] = n + 1
    return n % every == 0


def _record(name, ts_us, dur_us, args):
    ev = {'name': name, 'ph': 'X', 'ts': round(ts_us, 3),
          'dur': round(dur_us, 3), 'pid': os.getpid(),
          'tid': threading.get_ident()}
    if args:
        ev['args'] = args
    with _lock:
        if len(_events) >= MAX_TRACE_EVENTS:
            _dropped[0] += 1
            return
        _events.append(ev)


class Span:
    """Reentrant-per-instance context manager; use via ``span(name, ...)``.

    The jax.profiler bridge engages whenever a device trace is active —
    independent of the telemetry switch — so ``utils.profiler.annotate``
    keeps its xplane contract even with telemetry off; the Chrome-trace
    record is only kept while telemetry is enabled.
    """

    __slots__ = ('name', 'sync', 'args', '_t0', '_bridge', '_recording')

    def __init__(self, name, sync=None, **attrs):
        self.name = name
        self.sync = sync
        self.args = dict(attrs) if attrs else None
        self._t0 = 0.0
        self._bridge = None
        self._recording = False

    def __enter__(self):
        self._recording = state.enabled()
        if _device_trace_active():
            try:
                import jax
                self._bridge = jax.profiler.TraceAnnotation(self.name)
                self._bridge.__enter__()
            except Exception:
                self._bridge = None
        if self._recording:
            self._t0 = _now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._recording:
            if self.sync is not None and exc_type is None and \
                    _should_sync(self.name):
                try:
                    import jax
                    # a callable defers capture to exit time, for values
                    # that only exist once the wrapped block ran
                    val = self.sync() if callable(self.sync) else self.sync
                    if val is not None:
                        jax.block_until_ready(val)
                        self.args = dict(self.args or {})
                        self.args['synced'] = True
                except Exception:
                    pass
            t1 = _now_us()
            _record(self.name, self._t0, t1 - self._t0, self.args)
        if self._bridge is not None:
            self._bridge.__exit__(exc_type, exc, tb)
            self._bridge = None
        return False


def span(name, sync=None, **attrs):
    """Context manager timing a named host region (see module docstring)."""
    return Span(name, sync=sync, **attrs)


def _record_async(ph, name, aid, cat, args):
    if not state.enabled():
        return
    ev = {'name': name, 'ph': ph, 'cat': cat, 'id': str(aid),
          'ts': round(_now_us(), 3), 'pid': os.getpid(),
          'tid': threading.get_ident()}
    if args:
        ev['args'] = args
    with _lock:
        if len(_events) >= MAX_TRACE_EVENTS:
            _dropped[0] += 1
            return
        _events.append(ev)


def async_begin(name, aid, cat='async', **args):
    """Open one async lane: events sharing ``(cat, id)`` until the matching
    ``async_end`` render as a single connected flow in Perfetto."""
    _record_async('b', name, aid, cat, args or None)


def async_instant(name, aid, cat='async', **args):
    """A point milestone on an open async lane (``ph: 'n'``)."""
    _record_async('n', name, aid, cat, args or None)


def async_end(name, aid, cat='async', **args):
    _record_async('e', name, aid, cat, args or None)


def trace_events():
    with _lock:
        return list(_events)


def dropped():
    return _dropped[0]


def clear():
    with _lock:
        _events.clear()
        _sync_counts.clear()
        _dropped[0] = 0


def dump_chrome_trace(path):
    """Write buffered spans as a Chrome trace-event JSON array (loads in
    Perfetto / chrome://tracing). Returns the number of events written."""
    evs = trace_events()
    with open(path, 'w', encoding='utf-8') as f:
        json.dump(evs, f)
    return len(evs)
