"""Telemetry runtime state: the process-wide on/off switch and knobs.

Stdlib-only and import-cycle-free on purpose: every instrumented narrow
waist (``core.tensor``, ``static.executor``, ``io.dataloader``, ...) imports
the observability package at module load, so nothing here may import jax or
any other ``paddle_tpu`` module at import time.

Env vars (read once at import; ``enable()``/``disable()`` override):

- ``PADDLE_TPU_TELEMETRY=1``       turn telemetry on for the process
- ``PADDLE_TPU_TELEMETRY_DIR``     where exporters write events.jsonl /
                                   trace.json (default /tmp/paddle_tpu_telemetry)
- ``PADDLE_TPU_TELEMETRY_SYNC_EVERY``
                                   sampled block_until_ready cadence for
                                   spans carrying device values: sample the
                                   1st and every Nth occurrence of a span
                                   name (default 16; 0 disables syncing)

Mission-control knobs (docs/OBSERVABILITY.md, "Mission control"):

- ``PADDLE_TPU_TELEMETRY_HTTP``    port for the live ``/metrics`` +
                                   ``/healthz`` endpoint (0 = pick a free
                                   port; unset/empty = no endpoint)
- ``PADDLE_TPU_TELEMETRY_HTTP_HOST``
                                   bind address (default 127.0.0.1 — the
                                   endpoint is diagnostics, not a public
                                   service; bind wider explicitly)
- ``PADDLE_TPU_TELEMETRY_FLUSH_EVERY``
                                   per-rank flush cadence in seconds for
                                   the cross-rank files (default 1.0)
- ``PADDLE_TPU_TELEMETRY_RUN_DIR`` cluster run dir for per-rank telemetry
                                   files (default: the supervisor's run
                                   dir, passed via heartbeat env)

Time-series knobs (owned by ``timeseries.py``, docs/OBSERVABILITY.md,
"Time series + regression sentinel"):

- ``PADDLE_TPU_TELEMETRY_SAMPLE_EVERY``
                                   ring-sampler cadence in seconds for the
                                   in-run counter/gauge/histogram time
                                   series (default 1.0; 0 disables the
                                   sampler; off with telemetry off)
- ``PADDLE_TPU_TELEMETRY_TIMESERIES_CAP``
                                   ring capacity in samples (default 512 —
                                   ~8.5 min at the default cadence; memory
                                   stays O(cap) over arbitrarily long runs)
- ``PADDLE_TPU_RUNS_REGISTRY``     cross-run baseline registry path
                                   (``runs.jsonl``; see ``baseline.py`` /
                                   ``tools/perfwatch.py``)

Cost explorer / SLO / flight-recorder knobs (owned by ``costs.py`` /
``slo.py`` / ``flight.py``, catalogued here so one file documents the env
surface):

- ``PADDLE_TPU_DEVICE_PEAK_FLOPS`` / ``PADDLE_TPU_DEVICE_PEAK_BPS``
                                   roofline device peaks (see costs.py)
- ``PADDLE_TPU_HBM_BUDGET``        device memory budget in bytes for the
                                   doctor's memory_pressure detector
- ``PADDLE_TPU_SLO_MS`` / ``PADDLE_TPU_SLO_OBJECTIVE``
                                   default per-model latency SLO
- ``PADDLE_TPU_FLIGHT=0``          disable the always-on flight recorder
- ``PADDLE_TPU_FLIGHT_EVENTS``     flight ring capacity (default 512)
- ``PADDLE_TPU_FLIGHT_DIR``        where crash dumps land
"""
import os
import threading

_DEFAULT_DIR = '/tmp/paddle_tpu_telemetry'


def _env_int(name, default):
    try:
        return int(os.environ.get(name, '') or default)
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, '') or default)
    except ValueError:
        return default


class _State:
    def __init__(self):
        self.enabled = os.environ.get('PADDLE_TPU_TELEMETRY', '') == '1'
        self.log_dir = os.environ.get('PADDLE_TPU_TELEMETRY_DIR',
                                      _DEFAULT_DIR)
        self.sync_every = _env_int('PADDLE_TPU_TELEMETRY_SYNC_EVERY', 16)
        self.lock = threading.Lock()


_STATE = _State()


def enabled():
    """Cheap hot-path guard; every instrumentation site checks this first."""
    return _STATE.enabled


def enable(log_dir=None, sync_every=None):
    """Turn telemetry on (also installs the jax compile/retrace hooks)."""
    if log_dir is not None:
        _STATE.log_dir = log_dir
    if sync_every is not None:
        _STATE.sync_every = int(sync_every)
    _STATE.enabled = True
    from . import interpose
    interpose.install_jax_hooks()


def disable():
    """Turn telemetry off. Hooks stay registered (they are no-ops while
    disabled; jax.monitoring has no targeted unregister)."""
    _STATE.enabled = False


def log_dir():
    return _STATE.log_dir


def sync_every():
    return _STATE.sync_every


# -- mission-control knobs (read live: the supervisor sets the run-dir env
# for its children after this module was first imported) -------------------

def http_port():
    """Requested endpoint port, or None when no endpoint was asked for.
    0 means "pick a free port" (the server reports the bound one)."""
    raw = os.environ.get('PADDLE_TPU_TELEMETRY_HTTP', '')
    if raw == '':
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def http_host():
    return os.environ.get('PADDLE_TPU_TELEMETRY_HTTP_HOST', '127.0.0.1')


def flush_every():
    return _env_float('PADDLE_TPU_TELEMETRY_FLUSH_EVERY', 1.0)


def sample_every():
    """Time-series sampler cadence in seconds (0 disables the sampler)."""
    return _env_float('PADDLE_TPU_TELEMETRY_SAMPLE_EVERY', 1.0)


def timeseries_cap():
    """Ring capacity (samples) for the in-run time series."""
    return max(2, _env_int('PADDLE_TPU_TELEMETRY_TIMESERIES_CAP', 512))


def run_dir():
    """Cluster run dir for per-rank telemetry files: the explicit override,
    else the supervisor's heartbeat dir (set for every supervised rank),
    else None (not part of a cluster run)."""
    return (os.environ.get('PADDLE_TPU_TELEMETRY_RUN_DIR')
            or os.environ.get('PADDLE_TPU_HEARTBEAT_DIR') or None)


def rank_id():
    """This process's rank in the cluster (0 in a single-process run) —
    the ONE definition of the per-rank file-naming identity, shared by the
    flusher (telemetry_rank<R>.json) and the flight recorder
    (flight_rank<R>.json)."""
    try:
        return int(os.environ.get('PADDLE_TRAINER_ID', '0') or 0)
    except ValueError:
        return 0
