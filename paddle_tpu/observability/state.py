"""Telemetry runtime state: the process-wide on/off switch and knobs.

Stdlib-only and import-cycle-free on purpose: every instrumented narrow
waist (``core.tensor``, ``static.executor``, ``io.dataloader``, ...) imports
the observability package at module load, so nothing here may import jax or
any other ``paddle_tpu`` module at import time.

Env vars (read once at import; ``enable()``/``disable()`` override):

- ``PADDLE_TPU_TELEMETRY=1``       turn telemetry on for the process
- ``PADDLE_TPU_TELEMETRY_DIR``     where exporters write events.jsonl /
                                   trace.json (default /tmp/paddle_tpu_telemetry)
- ``PADDLE_TPU_TELEMETRY_SYNC_EVERY``
                                   sampled block_until_ready cadence for
                                   spans carrying device values: sample the
                                   1st and every Nth occurrence of a span
                                   name (default 16; 0 disables syncing)
"""
import os
import threading

_DEFAULT_DIR = '/tmp/paddle_tpu_telemetry'


def _env_int(name, default):
    try:
        return int(os.environ.get(name, '') or default)
    except ValueError:
        return default


class _State:
    def __init__(self):
        self.enabled = os.environ.get('PADDLE_TPU_TELEMETRY', '') == '1'
        self.log_dir = os.environ.get('PADDLE_TPU_TELEMETRY_DIR',
                                      _DEFAULT_DIR)
        self.sync_every = _env_int('PADDLE_TPU_TELEMETRY_SYNC_EVERY', 16)
        self.lock = threading.Lock()


_STATE = _State()


def enabled():
    """Cheap hot-path guard; every instrumentation site checks this first."""
    return _STATE.enabled


def enable(log_dir=None, sync_every=None):
    """Turn telemetry on (also installs the jax compile/retrace hooks)."""
    if log_dir is not None:
        _STATE.log_dir = log_dir
    if sync_every is not None:
        _STATE.sync_every = int(sync_every)
    _STATE.enabled = True
    from . import interpose
    interpose.install_jax_hooks()


def disable():
    """Turn telemetry off. Hooks stay registered (they are no-ops while
    disabled; jax.monitoring has no targeted unregister)."""
    _STATE.enabled = False


def log_dir():
    return _STATE.log_dir


def sync_every():
    return _STATE.sync_every
