"""In-run telemetry time series: a bounded ring sampler over the registry.

Everything the spine collects is point-in-time — ``registry.snapshot()`` is
one frame — so a page-pool leak, a qps cliff mid-soak, or compile growth
after warmup are invisible until a human compares two frames by hand. This
module adds the time dimension: a ``TimeSeriesSampler`` snapshots every
registered counter/gauge plus histogram p50/p99 at a fixed cadence
(``PADDLE_TPU_TELEMETRY_SAMPLE_EVERY``, default 1 s; off with telemetry
off) into a bounded ring (``PADDLE_TPU_TELEMETRY_TIMESERIES_CAP`` samples),
so memory stays O(cap) over arbitrarily long runs — the exact bug class
graftlint GL020 lints for.

Counters are **delta-encoded**: each sample stores the increment since the
previous sample (zero deltas are dropped), and deltas evicted off the ring
fold into a per-series base, so ``base + cumsum(deltas)`` always
reconstructs the true cumulative totals no matter how much history the
ring dropped.

Transport rides the existing mission-control flusher: ``RankFlusher``
writes ``export()`` as ``timeseries_rank<R>.json`` into the supervisor run
dir, ``aggregate.merged_timeseries`` merges ranks into per-series
timelines inside ``cluster_snapshot.json``, and the doctor's trend
detectors (``page_leak`` / ``latency_creep`` / ``qps_collapse`` /
``compile_creep``) read those timelines. ``tools/telemetry_dump.py
--timeline`` renders them as ASCII sparklines.

Stdlib-only; never imports jax or other paddle_tpu packages.
"""
import collections
import threading

from . import events, registry, state
from .state import rank_id

__all__ = ['TimeSeriesSampler', 'start_sampler', 'stop_sampler',
           'active_sampler', 'export_active', 'to_series', 'clear']

#: histogram stats carried per sample (the trend detectors' working set)
_HIST_KEYS = ('p50', 'p99', 'count')

_lock = threading.Lock()
_active = [None]


class TimeSeriesSampler:
    """Cadenced snapshots of the metrics registry in a bounded ring.

    ``sample_now()`` is the one sample site: a single ``state.enabled()``
    check while telemetry is off (the PR 3 overhead discipline), one
    registry snapshot plus dict bookkeeping while on. The sampling thread
    is a daemon off the step path — instrumented code never pays for it.
    """

    def __init__(self, interval=None, capacity=None):
        self.interval = (state.sample_every() if interval is None
                         else float(interval))
        self.capacity = (state.timeseries_cap() if capacity is None
                         else max(2, int(capacity)))
        # explicit ring (not deque(maxlen)): eviction must fold the
        # evicted counter deltas into the base so cumulative totals
        # survive the drop
        self._buf = collections.deque()
        self._base = {}            # counter name -> evicted-delta total
        self._last = {}            # counter name -> raw total at last sample
        self._sample_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    @property
    def n_samples(self):
        return len(self._buf)

    def sample_now(self):
        """Take one sample; returns True when one landed. The only work
        while telemetry is disabled is this first flag check."""
        if not state.enabled():
            return False
        snap = registry.snapshot()
        ts = round(events.wall_ts(), 6)
        with self._sample_lock:
            deltas = {}
            for name, total in snap['counters'].items():
                if not isinstance(total, (int, float)):
                    continue
                d = total - self._last.get(name, 0)
                self._last[name] = total
                if d:
                    deltas[name] = round(d, 6) if isinstance(d, float) else d
            gauges = {k: v for k, v in snap['gauges'].items()
                      if isinstance(v, (int, float))}
            hists = {name: {k: st.get(k, 0) for k in _HIST_KEYS}
                     for name, st in snap['histograms'].items()
                     if st.get('count')}
            self._buf.append({'ts': ts, 'counters': deltas,
                              'gauges': gauges, 'histograms': hists})
            while len(self._buf) > self.capacity:
                evicted = self._buf.popleft()
                for name, d in evicted['counters'].items():
                    self._base[name] = self._base.get(name, 0) + d
        return True

    def export(self):
        """The per-rank document the flusher commits as
        ``timeseries_rank<R>.json`` (None while the ring is empty)."""
        with self._sample_lock:
            if not self._buf:
                return None
            return {
                'rank': rank_id(),
                'sample_every': self.interval,
                'capacity': self.capacity,
                'counters_base': dict(self._base),
                'samples': [dict(s) for s in self._buf],
            }

    def clear(self):
        with self._sample_lock:
            self._buf.clear()
            self._base.clear()
            self._last.clear()

    # -- cadence thread --------------------------------------------------
    def _run(self):
        while not self._stop.wait(self.interval):
            self.sample_now()

    def start(self):
        if self._thread is None and self.interval > 0:
            self._thread = threading.Thread(
                target=self._run, name='paddle-tpu-telemetry-sample',
                daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            from ..resilience.watchdog import join_thread
            join_thread(t, timeout=max(self.interval * 4, 2.0))
            self._thread = None


def start_sampler(interval=None):
    """Start (or return) the process-wide sampler. None when telemetry is
    disabled or the cadence knob is 0 (sampler off)."""
    if not state.enabled():
        return None
    with _lock:
        sm = _active[0]
        if sm is not None:
            return sm
        sm = TimeSeriesSampler(interval=interval)
        if sm.interval <= 0:
            return None
        _active[0] = sm.start()
        return _active[0]


def stop_sampler():
    """Stop the cadence thread; the ring keeps its samples (the final
    flush still exports them)."""
    with _lock:
        sm = _active[0]
    if sm is not None:
        sm.stop()


def active_sampler():
    return _active[0]


def export_active():
    sm = _active[0]
    return sm.export() if sm is not None else None


def clear():
    """Drop the process-wide sampler and its ring (test isolation)."""
    with _lock:
        sm, _active[0] = _active[0], None
    if sm is not None:
        sm.stop()
        sm.clear()


def to_series(doc, rank=None):
    """Per-series timelines from one rank's export document — the same
    shape ``aggregate.merged_timeseries`` builds cluster-wide:
    ``{'counter:<name>'|'gauge:<name>'|'hist:<name>:<stat>':
    {rank: [[ts, value], ...]}}``. Counter timelines carry reconstructed
    cumulative totals (``base + cumsum(deltas)``)."""
    series = {}
    if not isinstance(doc, dict):
        return series
    r = doc.get('rank', 0) if rank is None else rank
    cum = dict(doc.get('counters_base') or {})
    for s in doc.get('samples') or []:
        if not isinstance(s, dict):
            continue
        ts = s.get('ts', 0)
        for name, d in (s.get('counters') or {}).items():
            if isinstance(d, (int, float)):
                cum[name] = cum.get(name, 0) + d
        # dense counter timelines: a sample with no delta still contributes
        # its (unchanged) cumulative point — a qps cliff IS the run of
        # flat points, and dropping them would hide exactly that
        for name, total in cum.items():
            series.setdefault(f'counter:{name}', {}) \
                .setdefault(r, []).append([ts, total])
        for name, v in (s.get('gauges') or {}).items():
            if isinstance(v, (int, float)):
                series.setdefault(f'gauge:{name}', {}) \
                    .setdefault(r, []).append([ts, v])
        for name, st in (s.get('histograms') or {}).items():
            if not isinstance(st, dict):
                continue
            for k in _HIST_KEYS:
                v = st.get(k)
                if isinstance(v, (int, float)):
                    series.setdefault(f'hist:{name}:{k}', {}) \
                        .setdefault(r, []).append([ts, v])
    return series
