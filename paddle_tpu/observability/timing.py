"""Timing helpers: the sanctioned replacement for raw ``time.time()`` /
``time.perf_counter()`` timing in library code (enforced by graftlint GL011).

- ``Stopwatch``: a monotonic elapsed-time reader for code that needs the
  number itself (progress bars, deadline math, autotuners).
- ``timer(name)``: context manager that times a block into the metrics
  registry (histogram ``<name>_ms`` + counter ``<name>.calls``) and emits a
  span — one line at a call site, and the duration is visible in the
  Prometheus exposition, the snapshot, and the Chrome trace at once.

Timestamps (as opposed to durations) come from ``events.wall_ts()``.
"""
import time

from . import registry, spans, state

__all__ = ['Stopwatch', 'timer']


class Stopwatch:
    """Monotonic elapsed-time reader; starts at construction.

    ``perf_counter``-backed: immune to wall-clock steps (NTP), valid only
    for durations within one process.
    """

    __slots__ = ('_t0',)

    def __init__(self):
        self._t0 = time.perf_counter()

    def restart(self):
        self._t0 = time.perf_counter()

    def elapsed(self):
        """Seconds since construction/restart."""
        return time.perf_counter() - self._t0

    def elapsed_ms(self):
        return self.elapsed() * 1e3


class _Timer:
    __slots__ = ('name', '_span', '_sw', 'elapsed_ms')

    def __init__(self, name, sync=None, **attrs):
        self.name = name
        self._span = spans.Span(name, sync=sync, **attrs)
        self._sw = None
        self.elapsed_ms = 0.0

    def __enter__(self):
        self._span.__enter__()
        self._sw = Stopwatch()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.elapsed_ms = self._sw.elapsed_ms()
        out = self._span.__exit__(exc_type, exc, tb)
        if state.enabled():
            registry.counter(self.name + '.calls').inc()
            registry.histogram(self.name + '_ms').observe(self.elapsed_ms)
        return out


def timer(name, sync=None, **attrs):
    """Time a block into the registry + span buffer (no-op when disabled
    beyond a Stopwatch read). ``sync`` follows the span sampled-sync rule."""
    return _Timer(name, sync=sync, **attrs)
