"""paddle_tpu.optimizer. Parity: python/paddle/optimizer/__init__.py."""
from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adamax,
                        Adadelta, Adagrad, RMSProp, Lamb, LarsMomentum, Ftrl)
from . import lr
from .lr import *  # noqa
from .extras import ExponentialMovingAverage, LookAhead, ModelAverage
from .fused import FlatFusedUpdate
