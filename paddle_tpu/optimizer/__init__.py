"""paddle_tpu.optimizer. Parity: python/paddle/optimizer/__init__.py."""
from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adamax,
                        Adadelta, Adagrad, RMSProp, Lamb, LarsMomentum, Ftrl,
                        DecayedAdagrad, DecayedAdagradOptimizer,
                        Dpsgd, DpsgdOptimizer)
from . import lr
from .lr import *  # noqa
from .extras import (ExponentialMovingAverage, LookAhead, ModelAverage,
                     PipelineOptimizer, RecomputeOptimizer)
from .fused import FlatFusedUpdate

# -- 1.8 *Optimizer aliases + 2.0-beta *LR scheduler names -------------------
MomentumOptimizer = Momentum
AdagradOptimizer = Adagrad
AdadeltaOptimizer = Adadelta
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl
LambOptimizer = Lamb
LarsMomentumOptimizer = LarsMomentum
SGDOptimizer = SGD
DGCMomentumOptimizer = Momentum   # dgc = bf16-compressed allreduce knob
LookaheadOptimizer = LookAhead
ModelAverageOptimizer = ModelAverage

from .lr import (NoamDecay as NoamLR,  # noqa: F401,E402
                 PiecewiseDecay as PiecewiseLR,
                 NaturalExpDecay as NaturalExpLR,
                 InverseTimeDecay as InverseTimeLR,
                 PolynomialDecay as PolynomialLR,
                 LinearWarmup as LinearLrWarmup,
                 ExponentialDecay as ExponentialLR,
                 MultiStepDecay as MultiStepLR,
                 StepDecay as StepLR,
                 LambdaDecay as LambdaLR,
                 ReduceOnPlateau as ReduceLROnPlateau,
                 CosineAnnealingDecay as CosineAnnealingLR)


from . import lr_scheduler  # noqa: E402,F401  (2.0-beta module path)
from .lr_scheduler import _LRScheduler  # noqa: E402,F401
