"""paddle_tpu.optimizer. Parity: python/paddle/optimizer/__init__.py."""
from .optimizer import (Optimizer, SGD, Momentum, Adam, AdamW, Adamax,
                        Adadelta, Adagrad, RMSProp, Lamb, LarsMomentum, Ftrl)
from . import lr
from .lr import *  # noqa
from .extras import ExponentialMovingAverage, LookAhead, ModelAverage
from .fused import FlatFusedUpdate

# -- 1.8 *Optimizer aliases + 2.0-beta *LR scheduler names -------------------
MomentumOptimizer = Momentum
AdagradOptimizer = Adagrad
AdadeltaOptimizer = Adadelta
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl
LambOptimizer = Lamb
LarsMomentumOptimizer = LarsMomentum
SGDOptimizer = SGD
DecayedAdagrad = Adagrad          # decay handled by lr schedulers here
DecayedAdagradOptimizer = Adagrad
DGCMomentumOptimizer = Momentum   # dgc = bf16-compressed allreduce knob
Dpsgd = SGD                       # differential-privacy noise not ported
DpsgdOptimizer = SGD
LookaheadOptimizer = LookAhead
ModelAverageOptimizer = ModelAverage

from .lr import (NoamDecay as NoamLR,  # noqa: F401,E402
                 PiecewiseDecay as PiecewiseLR,
                 NaturalExpDecay as NaturalExpLR,
                 InverseTimeDecay as InverseTimeLR,
                 PolynomialDecay as PolynomialLR,
                 LinearWarmup as LinearLrWarmup,
                 ExponentialDecay as ExponentialLR,
                 MultiStepDecay as MultiStepLR,
                 StepDecay as StepLR,
                 LambdaDecay as LambdaLR,
                 ReduceOnPlateau as ReduceLROnPlateau,
                 CosineAnnealingDecay as CosineAnnealingLR)


def PipelineOptimizer(optimizer, num_microbatches=1, **kw):
    """1.8 pipeline wrapper: microbatching lives in
    distributed.pipeline.pipeline_apply here; the optimizer passes through
    unchanged (kept callable so fleet scripts construct it)."""
    return optimizer


def RecomputeOptimizer(optimizer, **kw):
    """1.8 recompute wrapper: rematerialization is fleet's recompute knob
    (jax.checkpoint); the optimizer passes through unchanged."""
    return optimizer
from . import lr_scheduler  # noqa: E402,F401  (2.0-beta module path)
from .lr_scheduler import _LRScheduler  # noqa: E402,F401
