"""EMA / LookAhead / ModelAverage. Parity: fluid/optimizer.py extras."""
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.autograd import no_grad


class ExponentialMovingAverage:
    """Parity: fluid/optimizer.py:ExponentialMovingAverage."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._step = 0
        self._params = []

    def register(self, parameters):
        self._params = list(parameters)
        for p in self._params:
            self._shadow[id(p)] = p._value

    @no_grad()
    def update(self, parameters=None):
        params = list(parameters) if parameters is not None else self._params
        if not self._shadow:
            self.register(params)
        self._step += 1
        decay = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in params:
            old = self._shadow.get(id(p), p._value)
            self._shadow[id(p)] = decay * old + (1 - decay) * p._value

    def apply(self, executor=None, need_restore=True):
        for p in self._params:
            self._backup[id(p)] = p._value
            p._inplace_value(self._shadow[id(p)])
        return _EMAGuard(self) if need_restore else None

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._inplace_value(self._backup[id(p)])
        self._backup = {}


class _EMAGuard:
    def __init__(self, ema):
        self._ema = ema

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._ema.restore()
        return False


class LookAhead:
    """Parity: incubate LookAhead: slow weights sync every k steps."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._slow = {}
        self._step = 0

    @no_grad()
    def step(self):
        self.inner_optimizer.step()
        self._step += 1
        params = self.inner_optimizer._parameters or []
        if not self._slow:
            for p in params:
                self._slow[id(p)] = p._value
        if self._step % self.k == 0:
            for p in params:
                slow = self._slow[id(p)] + self.alpha * (p._value -
                                                         self._slow[id(p)])
                self._slow[id(p)] = slow
                p._inplace_value(slow)

    def minimize(self, loss):
        loss.backward()
        self.step()
        self.clear_grad()

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def state_dict(self):
        return self.inner_optimizer.state_dict()


class ModelAverage:
    """Sliding-window parameter average. Parity: fluid ModelAverage."""

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000):
        self._params = list(parameters) if parameters else []
        self._sum = {id(p): jnp.zeros_like(p._value) for p in self._params}
        self._num = 0
        self._backup = {}
        self.max_average_window = max_average_window

    @no_grad()
    def step(self):
        self._num += 1
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p._value

    def apply(self, executor=None, need_restore=True):
        for p in self._params:
            self._backup[id(p)] = p._value
            p._inplace_value(self._sum[id(p)] / max(self._num, 1))
        return _MAGuard(self) if need_restore else None

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._inplace_value(self._backup[id(p)])
        self._backup = {}


class _MAGuard:
    def __init__(self, ma):
        self._ma = ma

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._ma.restore()
        return False


class PipelineOptimizer:
    """1.8 pipeline-training wrapper. Parity: fluid/optimizer.py:3666.

    TPU-first divergence: the reference splits the Program into
    device-pinned sections with a microbatch schedule (C++ Section
    trainers); here pipeline parallelism lives in
    :func:`paddle_tpu.distributed.pipeline.pipeline_apply` (GPipe over a
    'pipe' mesh axis inside one XLA program). This wrapper keeps the 1.8
    script shape: it validates the config and delegates optimization to
    the inner optimizer — `num_microbatches` is honored by the mesh
    pipeline, not a host scheduler.
    """

    def __init__(self, optimizer, num_microbatches=1, start_cpu_core_id=0):
        if num_microbatches < 1:
            raise ValueError("num_microbatches must be a positive value.")
        if start_cpu_core_id < 0:
            raise ValueError(
                "start_cpu_core_id must be greater than or equal to 0.")
        self._optimizer = optimizer
        self._num_microbatches = num_microbatches
        self._start_cpu_core_id = start_cpu_core_id

    def __getattr__(self, name):
        return getattr(self._optimizer, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._optimizer.minimize(loss, startup_program,
                                        parameter_list, no_grad_set)


class RecomputeOptimizer:
    """1.8 recompute (activation-checkpointing) wrapper. Parity:
    fluid/optimizer.py:4518.

    TPU-first divergence: the reference rewrites the backward pass to
    recompute forward segments between user checkpoints; under XLA the
    equivalent is :func:`paddle_tpu.distributed.recompute` /
    ``jax.checkpoint`` around model blocks, which the compiler schedules.
    The wrapper preserves the script API (`_set_checkpoints`, `backward`,
    `apply_gradients`, `apply_optimize`, `minimize`) and records the
    checkpoint variables for introspection.
    """

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        if not isinstance(checkpoints, (list, tuple)):
            raise ValueError("checkpoints should be a list or tuple")
        self._checkpoints = list(checkpoints)

    def __getattr__(self, name):
        return getattr(self._optimizer, name)

    def load(self, state_dict):
        raise NotImplementedError(
            "RecomputeOptimizer.load is not supported (the reference raises "
            "here too); call set_state_dict on the inner optimizer")

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return self._optimizer.backward(loss, startup_program,
                                        parameter_list, no_grad_set)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def apply_optimize(self, loss, startup_program, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._optimizer.minimize(loss, startup_program,
                                        parameter_list, no_grad_set)
