"""Flat-buffer fused optimizer update.

Role of the reference's fused optimizer ops
(paddle/fluid/operators/optimizers/*): pack every fp32 parameter into ONE
master buffer (plus matching moment buffers) so the whole update runs as a
single streaming elementwise pass, and per-param eager copies can be freed
(the master buffer owns the weights).

Measured caveat (TPU v5e, BERT-large single-chip train step): inside one
jitted train step XLA overlaps the ~400 per-tensor update fusions with the
tail of the backward pass, so the flat update's bandwidth win is offset by
its serialization behind the full gradient — the per-param path benched
slightly FASTER end-to-end (tools/bench_2x2.py). Use this when updates
cannot overlap (e.g. gradient-accumulation boundaries, sharded ZeRO updates
applied after a reduce-scatter, host-offloaded optimizer states) or when the
1.36 GB of freed eager param copies is what lets the batch fit.

Layout: the master buffer is 2-D ``(rows, 128*8)`` — the TPU's native tile
minor dimension — with every parameter's segment padded to whole rows. A
giant 1-D buffer triggers pathological padded layouts in XLA's TPU layout
assignment (observed: bf16[N/2, 2] padded x64 -> 43 GB); row-packing avoids
the entire class of problem and makes per-param slices static row ranges.

Works with any Optimizer whose ``_rule`` is elementwise (SGD/Momentum/Adam/
AdamW/...). AdamW's decay predicate becomes a precomputed 0/1 mask buffer.
"""
import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['FlatFusedUpdate']

_LANE = 1024  # 8 sublanes x 128 lanes: one full fp32 TPU tile per row


class FlatFusedUpdate:
    """Pack a {name: fp32 array} tree into one (rows, 1024) master buffer
    and run the optimizer rule as a single fused update.

    Usage (pure/functional, jit-friendly)::

        flat = FlatFusedUpdate(opt, params)        # params: name -> f32 array
        flat_p = flat.flatten(params)
        state = flat.init_state(flat_p)
        ...
        tree_p = flat.unflatten(flat_p)            # for the forward pass
        new_flat_p, state = flat.update(flat_p, grads_tree, state)
    """

    def __init__(self, opt, param_values, decay_mask=None):
        self.opt = opt
        self.names = sorted(param_values)
        self.shapes = {k: tuple(np.shape(param_values[k])) for k in self.names}
        self.sizes = {k: int(np.prod(self.shapes[k])) if self.shapes[k]
                      else 1 for k in self.names}
        self.row_off = {}     # first row of each param's padded segment
        self.row_cnt = {}     # rows in the segment
        rows = 0
        for k in self.names:
            self.row_off[k] = rows
            self.row_cnt[k] = -(-self.sizes[k] // _LANE)   # ceil div
            rows += self.row_cnt[k]
        self.rows = rows
        self._decay_mask_buf = None
        if decay_mask is not None:
            from .optimizer import AdamW
            if not isinstance(opt, AdamW):
                raise ValueError(
                    "decay_mask implements AdamW's decoupled decay predicate;"
                    f" it has no effect for {type(opt).__name__} — drop it or"
                    " use AdamW")
            vec = np.zeros((rows, _LANE), np.float32)
            for k in self.names:
                if decay_mask(k):
                    r0, rc = self.row_off[k], self.row_cnt[k]
                    seg = np.zeros((rc * _LANE,), np.float32)
                    seg[:self.sizes[k]] = 1.0
                    vec[r0:r0 + rc] = seg.reshape(rc, _LANE)
            self._decay_mask_buf = jnp.asarray(vec)

    # -- layout ------------------------------------------------------------
    def flatten(self, tree, dtype=jnp.float32):
        """Pack tree leaves (name order) into the (rows, 1024) buffer."""
        segs = []
        for k in self.names:
            v = jnp.ravel(tree[k]).astype(dtype)
            pad = self.row_cnt[k] * _LANE - self.sizes[k]
            if pad:
                v = jnp.concatenate([v, jnp.zeros((pad,), dtype)])
            segs.append(v.reshape(self.row_cnt[k], _LANE))
        return jnp.concatenate(segs, axis=0)

    def unflatten(self, flat, dtype=None):
        """Slice the master buffer back into the named/shaped tree."""
        out = {}
        for k in self.names:
            r0, rc = self.row_off[k], self.row_cnt[k]
            v = jnp.ravel(flat[r0:r0 + rc])[:self.sizes[k]]
            v = v.reshape(self.shapes[k])
            out[k] = v.astype(dtype) if dtype is not None else v
        return out

    # -- optimizer ---------------------------------------------------------
    def init_state(self, flat_p):
        return self.opt._init_state(flat_p)

    def update(self, flat_p, grads_tree, state, lr=None):
        """One fused elementwise update over the whole parameter buffer."""
        lr = self.opt.get_lr() if lr is None else lr
        g = (grads_tree if getattr(grads_tree, 'ndim', None) == 2
             else self.flatten(grads_tree))
        # coupled (L2) weight decay, same semantics as functional_update's
        # grad_term path — without this, Momentum/SGD weight_decay would be
        # silently dropped on the flat path
        from ..nn.regularizer import WeightDecayRegularizer
        wd = getattr(self.opt, '_weight_decay', None)
        if isinstance(wd, WeightDecayRegularizer):
            g = g + wd.grad_term(flat_p)
        if self._decay_mask_buf is not None:
            # run the base rule without decoupled decay, then apply masked
            # decay (AdamW): p -= lr * coeff * mask * p
            from .optimizer import Adam, AdamW
            if isinstance(self.opt, AdamW):
                new_p, st = Adam._rule(self.opt, g, flat_p, state, lr)
                new_p = new_p - lr * self.opt._coeff * \
                    self._decay_mask_buf * flat_p
                return new_p, st
        return self.opt._rule(g, flat_p, state, lr)
