"""2.0-beta ``paddle.optimizer.lr_scheduler`` module path.

Parity: python/paddle/optimizer/lr_scheduler.py:27 — the beta shipped the
scheduler base as ``_LRScheduler`` in this module; the schedulers
themselves live in :mod:`paddle_tpu.optimizer.lr` (one implementation,
two import paths).
"""
from .lr import *  # noqa: F401,F403
from .lr import LRScheduler, __all__ as _lr_all

_LRScheduler = LRScheduler

__all__ = list(_lr_all) + ['_LRScheduler']
