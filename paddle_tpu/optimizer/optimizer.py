"""Optimizer base + concrete optimizers.

Parity: python/paddle/optimizer/*.py (+ fluid/optimizer.py extras: Lamb,
LarsMomentum, Ftrl, ModelAverage, EMA, LookAhead).

TPU-first design: every optimizer is defined by a pure per-parameter update
rule ``_rule(grad, param, state, lr) -> (new_param, new_state)``. The eager
``step()`` walks parameters applying the rule; the same rule powers the fully
jitted functional train step (``functional_update``), so eager and compiled
paths can't diverge.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core.autograd import no_grad
from ..nn.clip import ClipGradBase
from ..nn.regularizer import WeightDecayRegularizer
from .lr import LRScheduler
from .. import observability as _obs


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else None
        if isinstance(weight_decay, float):
            from ..nn.regularizer import L2Decay
            weight_decay = L2Decay(weight_decay)
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._accumulators = {}  # param name -> state dict
        self._global_step = 0

    # -- lr -----------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return self._lr()
        return self._lr

    def set_lr(self, value):
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("can't set_lr when using an LRScheduler")
        self._lr = float(value)

    @property
    def _learning_rate(self):
        return self._lr

    # -- state --------------------------------------------------------------
    def _param_state(self, p):
        key = p.name or str(id(p))
        if key not in self._accumulators:
            self._accumulators[key] = self._init_state(p._value)
        return key, self._accumulators[key]

    def _init_state(self, value):
        return {}

    def state_dict(self):
        out = {}
        # emit groups in parameter order (not first-grad order) so a
        # positional restore into a renamed model lines up correctly
        order = [p.name for p in (self._parameters or [])
                 if p.name in self._accumulators]
        order += [n for n in self._accumulators if n not in order]
        for pname in order:
            for sname, v in self._accumulators[pname].items():
                out[f"{pname}.{sname}"] = Tensor(v) if not isinstance(v, Tensor) \
                    else v
        out['global_step'] = self._global_step
        if isinstance(self._lr, LRScheduler):
            out['LR_Scheduler'] = self._lr.state_dict()
        return out

    def set_state_dict(self, state_dict):
        self._global_step = int(state_dict.get('global_step', 0))
        if 'LR_Scheduler' in state_dict and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state_dict['LR_Scheduler'])
        grouped = {}   # saved pname -> {sname: val}, insertion-ordered
        for k, v in state_dict.items():
            if k in ('global_step', 'LR_Scheduler'):
                continue
            pname, _, sname = k.rpartition('.')
            val = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            grouped.setdefault(pname, {})[sname] = val
        # Saved keys embed parameter names from the run that produced them;
        # a fresh model instance gets new unique_name suffixes, so match by
        # position (state_dict emits groups in parameter order) when names
        # don't line up — otherwise the restored slots would sit unused and
        # step() would silently re-create zeros. Every per-element slot must
        # match its target parameter's shape; a mismatch means the checkpoint
        # belongs to a different model, which must fail loudly, not scramble.
        cur_params = list(self._parameters or [])
        cur_names = [p.name for p in cur_params]
        overlap = set(grouped) & set(cur_names)
        if cur_names and not overlap and len(grouped) == len(cur_names):
            # fully disjoint name sets: a renamed instance of the same model
            for p, (old, slots) in zip(cur_params, grouped.items()):
                for sname, v in slots.items():
                    if v.ndim > 0 and tuple(v.shape) != tuple(p.shape):
                        raise ValueError(
                            "optimizer.set_state_dict: cannot positionally "
                            "map saved state '%s.%s' (shape %s) onto "
                            "parameter '%s' (shape %s); the checkpoint was "
                            "saved from a different model" %
                            (old, sname, tuple(v.shape), p.name,
                             tuple(p.shape)))
            grouped = {cn: sv for cn, sv in zip(cur_names, grouped.values())}
        elif cur_names and grouped and not overlap:
            # disjoint names but counts differ: no name matches and a
            # positional map would be a guess — fail loudly, the state
            # would otherwise sit unused and step() would re-zero it.
            raise ValueError(
                "optimizer.set_state_dict: none of the %d saved state "
                "group(s) match the %d current parameter(s) by name, and "
                "the counts differ so they cannot be mapped positionally "
                "(saved e.g. %s; current e.g. %s)"
                % (len(grouped), len(cur_names),
                   sorted(grouped)[:3], cur_names[:3]))
        elif cur_names and overlap and set(grouped) != set(cur_names):
            # partial overlap: restore the by-name matches, warn about any
            # leftovers — never guess positionally here. (A strict subset
            # of current names is a valid lazy-accumulator checkpoint.)
            unmatched = sorted(set(grouped) - set(cur_names))
            if unmatched:
                import warnings
                warnings.warn(
                    "optimizer.set_state_dict: %d saved state group(s) have "
                    "no matching parameter and were ignored: %s"
                    % (len(unmatched), unmatched[:5]))
                grouped = {k: v for k, v in grouped.items()
                           if k in cur_names}
        # by-name restores get the same loud shape validation the positional
        # path has: a same-named param of a different shape means the
        # checkpoint came from a different model.
        by_name = {p.name: p for p in cur_params}
        for pname, slots in grouped.items():
            p = by_name.get(pname)
            if p is not None:
                for sname, v in slots.items():
                    if v.ndim > 0 and tuple(v.shape) != tuple(p.shape):
                        raise ValueError(
                            "optimizer.set_state_dict: saved state '%s.%s' "
                            "has shape %s but parameter '%s' has shape %s; "
                            "the checkpoint was saved from a different model"
                            % (pname, sname, tuple(v.shape), pname,
                               tuple(p.shape)))
            self._accumulators.setdefault(pname, {}).update(slots)

    set_dict = set_state_dict

    # -- decay/clip plumbing -------------------------------------------------

    def _effective_grad_clip(self):
        """Constructor grad_clip, else the fluid.clip.set_gradient_clip
        process default (1.8 global-clip API)."""
        if self._grad_clip is not None:
            return self._grad_clip
        try:
            from ..fluid.clip import get_gradient_clip
            return get_gradient_clip()
        except ImportError:
            return None

    def _apply_decay_and_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            reg = p.regularizer if p.regularizer is not None else \
                self._weight_decay
            if isinstance(reg, WeightDecayRegularizer):
                g = g + reg.grad_term(p._value)
            out.append((p, g))
        clip = self._effective_grad_clip()
        if clip is not None:
            out = clip(out)
        return out

    # -- stepping ------------------------------------------------------------
    @no_grad()
    def step(self):
        params = self._parameters
        if params is None:
            raise ValueError("Optimizer created without parameters; pass "
                             "parameters=model.parameters()")
        with _obs.timer('optimizer.step', optimizer=type(self).__name__):
            params_grads = [(p, p.grad._value) for p in params
                            if p.grad is not None and p.trainable]
            params_grads = self._apply_decay_and_clip(params_grads)
            lr = self.get_lr()
            for p, g in params_grads:
                key, state = self._param_state(p)
                p_lr = lr * p.optimize_attr.get('learning_rate', 1.0)
                new_val, new_state = self._rule(g, p._value, state, p_lr)
                p._inplace_value(new_val)
                self._accumulators[key] = new_state
            self._global_step += 1

    _static_state = None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        if getattr(loss, '_symbolic', False):
            # static-graph mode: mark the program for train compilation
            # (Executor lowers forward+grad+update into one XLA program).
            from ..static.graph import current_capture_program
            prog = current_capture_program()
            prog._train_spec = (loss, self)
            return [], []
        loss.backward()
        self.step()
        self.clear_grad()
        return [], []

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        """1.8 split-phase API: compute grads, return [(param, grad)].
        Parity: fluid/optimizer.py Optimizer.backward."""
        if getattr(loss, '_symbolic', False):
            # remember the loss so a following apply_gradients can record
            # the train spec the way minimize() does (static mode has no
            # eager step to run)
            self._pending_static_loss = loss
            from ..fluid.backward import append_backward
            return append_backward(loss, parameter_list, no_grad_set)
        loss.backward()
        params = parameter_list or self._parameters or []
        return [(p, p.grad) for p in params if p.grad is not None]

    def apply_gradients(self, params_grads):
        """1.8 split-phase API: apply pre-computed [(param, grad)] pairs —
        the pairs GIVEN, overwriting any stored grad (callers transform
        grads between backward and apply). Parity: fluid/optimizer.py
        Optimizer.apply_gradients."""
        params_grads = list(params_grads)
        if any(getattr(g, '_symbolic', False) for _, g in params_grads
               if g is not None):
            loss = getattr(self, '_pending_static_loss', None)
            if loss is None:
                raise RuntimeError(
                    "apply_gradients got symbolic gradients but no "
                    "preceding backward(loss) on this optimizer — in "
                    "static mode call backward() first (or minimize())")
            return self.apply_optimize(loss, None, params_grads)
        saved = self._parameters
        try:
            self._parameters = [p for p, _ in params_grads]
            for p, g in params_grads:
                if g is not None:
                    p._grad = g if isinstance(g, Tensor) else Tensor(g)
            self.step()
        finally:
            self._parameters = saved
        return []

    def apply_optimize(self, loss, startup_program, params_grads):
        if getattr(loss, '_symbolic', False):
            # static mode: record the train spec like minimize() — the
            # Executor lowers forward+grad+update into one XLA program
            from ..static.graph import current_capture_program
            prog = current_capture_program()
            prog._train_spec = (loss, self)
            self._pending_static_loss = None
            return []
        return self.apply_gradients(params_grads)

    def clear_grad(self):
        if self._parameters is not None:
            for p in self._parameters:
                p.clear_grad()

    clear_gradients = clear_grad

    # -- functional path (jitted train steps) --------------------------------
    def init_state_values(self, param_values):
        """param_values: dict name -> raw value. Returns state pytree."""
        return {k: self._init_state(v) for k, v in param_values.items()}

    def functional_update(self, param_values, grad_values, opt_state, lr=None,
                          params_meta=None):
        """Pure: (params, grads, state[, lr]) -> (new_params, new_state).

        params_meta: optional dict name -> Parameter for per-param lr /
        regularizer / clip metadata.
        """
        lr = self.get_lr() if lr is None else lr
        # decay
        if self._weight_decay is not None or params_meta:
            new_grads = {}
            for k, g in grad_values.items():
                reg = None
                if params_meta is not None and k in params_meta:
                    reg = params_meta[k].regularizer
                if reg is None:
                    reg = self._weight_decay
                if isinstance(reg, WeightDecayRegularizer):
                    g = g + reg.grad_term(param_values[k])
                new_grads[k] = g
            grad_values = new_grads
        _clip = self._effective_grad_clip()
        if _clip is not None:
            class _Meta:
                need_clip = True
            meta = _Meta()
            pairs = [(params_meta[k] if params_meta and k in params_meta
                      else meta, grad_values[k]) for k in grad_values]
            clipped = _clip(pairs)
            grad_values = {k: g for k, (_, g) in zip(grad_values, clipped)}
        new_params, new_state = {}, {}
        for k, g in grad_values.items():
            st = opt_state.get(k, self._init_state(param_values[k]))
            p_lr = lr
            if params_meta is not None and k in params_meta:
                p_lr = lr * params_meta[k].optimize_attr.get('learning_rate', 1.0)
            new_params[k], new_state[k] = self._rule(g, param_values[k], st, p_lr)
        for k, v in param_values.items():
            if k not in new_params:
                new_params[k] = v
                if k in opt_state:
                    new_state[k] = opt_state[k]
        return new_params, new_state

    def _rule(self, g, p, state, lr):
        raise NotImplementedError


class SGD(Optimizer):
    def _rule(self, g, p, state, lr):
        return p - lr * g.astype(p.dtype), state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, value):
        return {'velocity': jnp.zeros_like(value)}

    def _rule(self, g, p, state, lr):
        g = g.astype(p.dtype)
        v = self._momentum * state['velocity'] + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {'velocity': v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, name=None, amsgrad=False):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        self._amsgrad = amsgrad

    def _init_state(self, value):
        st = {'moment1': jnp.zeros_like(value),
              'moment2': jnp.zeros_like(value),
              'beta1_pow': jnp.ones((), value.dtype),
              'beta2_pow': jnp.ones((), value.dtype)}
        if self._amsgrad:
            st['moment2_max'] = jnp.zeros_like(value)
        return st

    def _rule(self, g, p, state, lr):
        g = g.astype(p.dtype)
        b1, b2, eps = self._beta1, self._beta2, self._eps
        m = b1 * state['moment1'] + (1 - b1) * g
        v = b2 * state['moment2'] + (1 - b2) * g * g
        b1p = state['beta1_pow'] * b1
        b2p = state['beta2_pow'] * b2
        m_hat = m / (1 - b1p)
        if self._amsgrad:
            v_max = jnp.maximum(state['moment2_max'], v)
            v_hat = v_max / (1 - b2p)
        else:
            v_hat = v / (1 - b2p)
        new_p = p - lr * m_hat / (jnp.sqrt(v_hat) + eps)
        st = {'moment1': m, 'moment2': v, 'beta1_pow': b1p, 'beta2_pow': b2p}
        if self._amsgrad:
            st['moment2_max'] = v_max
        return new_p, st


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode)
        self._coeff = weight_decay if isinstance(weight_decay, float) else 0.01
        self._apply_decay_fn = apply_decay_param_fun

    def _rule(self, g, p, state, lr):
        new_p, st = super()._rule(g, p, state, lr)
        new_p = new_p - lr * self._coeff * p
        return new_p, st

    @no_grad()
    def step(self):
        # decoupled decay with per-param predicate
        params = self._parameters
        with _obs.timer('optimizer.step', optimizer=type(self).__name__):
            params_grads = [(p, p.grad._value) for p in params
                            if p.grad is not None and p.trainable]
            params_grads = self._apply_decay_and_clip(params_grads)
            lr = self.get_lr()
            for p, g in params_grads:
                key, state = self._param_state(p)
                p_lr = lr * p.optimize_attr.get('learning_rate', 1.0)
                decay = (self._apply_decay_fn is None or
                         self._apply_decay_fn(p.name))
                new_val, new_state = Adam._rule(self, g, p._value, state,
                                                p_lr)
                if decay:
                    new_val = new_val - p_lr * self._coeff * p._value
                p._inplace_value(new_val)
                self._accumulators[key] = new_state
            self._global_step += 1


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_state(self, value):
        return {'moment': jnp.zeros_like(value),
                'inf_norm': jnp.zeros_like(value),
                'beta1_pow': jnp.ones((), value.dtype)}

    def _rule(self, g, p, state, lr):
        g = g.astype(p.dtype)
        b1, b2, eps = self._beta1, self._beta2, self._eps
        m = b1 * state['moment'] + (1 - b1) * g
        u = jnp.maximum(b2 * state['inf_norm'], jnp.abs(g))
        b1p = state['beta1_pow'] * b1
        new_p = p - lr / (1 - b1p) * m / (u + eps)
        return new_p, {'moment': m, 'inf_norm': u, 'beta1_pow': b1p}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._eps = rho, epsilon

    def _init_state(self, value):
        return {'avg_squared_grad': jnp.zeros_like(value),
                'avg_squared_update': jnp.zeros_like(value)}

    def _rule(self, g, p, state, lr):
        g = g.astype(p.dtype)
        rho, eps = self._rho, self._eps
        asg = rho * state['avg_squared_grad'] + (1 - rho) * g * g
        update = g * jnp.sqrt(state['avg_squared_update'] + eps) / \
            jnp.sqrt(asg + eps)
        asu = rho * state['avg_squared_update'] + (1 - rho) * update * update
        return p - lr * update, {'avg_squared_grad': asg,
                                 'avg_squared_update': asu}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, name=None,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, value):
        return {'moment': jnp.full_like(value, self._init_acc)}

    def _rule(self, g, p, state, lr):
        g = g.astype(p.dtype)
        m = state['moment'] + g * g
        return p - lr * g / (jnp.sqrt(m) + self._eps), {'moment': m}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho, self._eps = rho, epsilon
        self._momentum = momentum
        self._centered = centered

    def _init_state(self, value):
        st = {'mean_square': jnp.zeros_like(value),
              'momentum': jnp.zeros_like(value)}
        if self._centered:
            st['mean_grad'] = jnp.zeros_like(value)
        return st

    def _rule(self, g, p, state, lr):
        g = g.astype(p.dtype)
        rho, eps = self._rho, self._eps
        ms = rho * state['mean_square'] + (1 - rho) * g * g
        if self._centered:
            mg = rho * state['mean_grad'] + (1 - rho) * g
            denom = jnp.sqrt(ms - mg * mg + eps)
        else:
            denom = jnp.sqrt(ms + eps)
        mom = self._momentum * state['momentum'] + lr * g / denom
        new_p = p - mom
        st = {'mean_square': ms, 'momentum': mom}
        if self._centered:
            st['mean_grad'] = mg
        return new_p, st


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, value):
        return {'moment1': jnp.zeros_like(value),
                'moment2': jnp.zeros_like(value),
                'beta1_pow': jnp.ones((), value.dtype),
                'beta2_pow': jnp.ones((), value.dtype)}

    def _rule(self, g, p, state, lr):
        g = g.astype(p.dtype)
        b1, b2, eps = self._beta1, self._beta2, self._eps
        m = b1 * state['moment1'] + (1 - b1) * g
        v = b2 * state['moment2'] + (1 - b2) * g * g
        b1p = state['beta1_pow'] * b1
        b2p = state['beta2_pow'] * b2
        m_hat = m / (1 - b1p)
        v_hat = v / (1 - b2p)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + self._wd * p
        w_norm = jnp.sqrt(jnp.sum(p * p))
        r_norm = jnp.sqrt(jnp.sum(r * r))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = p - lr * trust * r
        return new_p, {'moment1': m, 'moment2': v, 'beta1_pow': b1p,
                       'beta2_pow': b2p}


class LarsMomentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 epsilon=1e-9, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._momentum = momentum
        self._coeff = lars_coeff
        self._wd = lars_weight_decay
        self._eps = epsilon

    def _init_state(self, value):
        return {'velocity': jnp.zeros_like(value)}

    def _rule(self, g, p, state, lr):
        g = g.astype(p.dtype)
        w_norm = jnp.sqrt(jnp.sum(p * p))
        g_norm = jnp.sqrt(jnp.sum(g * g))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._coeff * w_norm / (g_norm + self._wd * w_norm + self._eps),
            1.0)
        v = self._momentum * state['velocity'] + \
            lr * local_lr * (g + self._wd * p)
        return p - v, {'velocity': v}


class Ftrl(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _init_state(self, value):
        return {'squared': jnp.zeros_like(value),
                'linear': jnp.zeros_like(value)}

    def _rule(self, g, p, state, lr):
        g = g.astype(p.dtype)
        n, z = state['squared'], state['linear']
        new_n = n + g * g
        sigma = (new_n ** -self._lr_power - n ** -self._lr_power) / lr
        new_z = z + g - sigma * p
        new_p = jnp.where(
            jnp.abs(new_z) <= self._l1, jnp.zeros_like(p),
            (jnp.sign(new_z) * self._l1 - new_z) /
            (new_n ** -self._lr_power / lr + 2 * self._l2))
        return new_p, {'squared': new_n, 'linear': new_z}


class DecayedAdagrad(Optimizer):
    """Adagrad with an exponentially DECAYED accumulator. Parity:
    fluid/optimizer.py DecayedAdagradOptimizer /
    operators/optimizers/decayed_adagrad_op.h:
    moment = decay*moment + (1-decay)*g^2; p -= lr * g / (sqrt(moment)+eps).
    """

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-06,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._decay, self._eps = decay, epsilon

    def _init_state(self, value):
        return {'moment': jnp.zeros_like(value)}

    def _rule(self, g, p, state, lr):
        g = g.astype(p.dtype)
        m = self._decay * state['moment'] + (1 - self._decay) * g * g
        return p - lr * g / (jnp.sqrt(m) + self._eps), {'moment': m}


class Dpsgd(Optimizer):
    """Differentially-private SGD (CCS16). Parity: fluid/optimizer.py:2264
    DpsgdOptimizer / operators/optimizers/dpsgd_op.h — per-tensor L2 clip
    (scale = max(1, ||g||/clip)) plus one shared gaussian noise sample
    N(0, sigma)/batch_size added to every element:
    p -= lr * (g/scale + noise/batch_size).

    The noise key lives in the optimizer STATE (split each step), so the
    rule stays pure and each jitted step draws fresh noise — a host-side
    RNG call here would be baked in at trace time.
    """

    def __init__(self, learning_rate=0.001, clip=0.9, batch_size=0.999,
                 sigma=1e-8, parameters=None, seed=0):
        super().__init__(learning_rate, parameters, None, None)
        self._dp_clip, self._batch_size, self._sigma = clip, batch_size, sigma
        self._seed = seed
        self._n_keys = 0

    def _init_state(self, value):
        import jax
        # fold a per-parameter INDEX in (init order is the deterministic
        # parameter order) so no two tensors share a noise stream —
        # element counts collide, indices cannot
        self._n_keys += 1
        key = jax.random.fold_in(jax.random.PRNGKey(self._seed),
                                 self._n_keys)
        return {'key': key}

    def _rule(self, g, p, state, lr):
        import jax
        g = g.astype(p.dtype)
        norm = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
        scale = jnp.maximum(norm / self._dp_clip, 1.0).astype(p.dtype)
        key, sub = jax.random.split(state['key'])
        noise = (jax.random.normal(sub, (), jnp.float32)
                 * self._sigma / self._batch_size).astype(p.dtype)
        return p - lr * (g / scale + noise), {'key': key}


DpsgdOptimizer = Dpsgd
DecayedAdagradOptimizer = DecayedAdagrad
