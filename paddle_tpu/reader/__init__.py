"""Reader-creator decorators. Parity: python/paddle/reader/decorator.py.

A *reader creator* is a zero-arg callable returning an iterator of samples —
the reference's original data-feeding abstraction, kept for API compat; the
TPU-first hot path is paddle_tpu.io.DataLoader, and these decorators are the
glue that lets legacy reader pipelines feed it.
"""
from .decorator import (map_readers, shuffle, chain, buffered, compose,
                        firstn, xmap_readers, cache, multiprocess_reader,
                        ComposeNotAligned)

__all__ = ['map_readers', 'shuffle', 'chain', 'buffered', 'compose',
           'firstn', 'xmap_readers', 'cache', 'multiprocess_reader',
           'ComposeNotAligned']
