"""Reader-creator decorators (behavioral parity with the reference's
python/paddle/reader/decorator.py, reimplemented for this runtime).

All functions take and return *reader creators*: ``creator() -> iterator``.
"""
import itertools
import queue
import random
import threading

from .. import observability as _obs
from ..resilience.watchdog import bounded_get, join_thread

__all__ = ['map_readers', 'shuffle', 'chain', 'buffered', 'compose',
           'firstn', 'xmap_readers', 'cache', 'multiprocess_reader',
           'ComposeNotAligned']


class ComposeNotAligned(ValueError):
    """Raised by compose(check_alignment=True) when the component readers
    yield different numbers of samples."""


def map_readers(func, *readers):
    """Zip several readers and map ``func`` over the tuples of samples:
    yields ``func(r1_sample, r2_sample, ...)``."""

    def reader():
        its = [r() for r in readers]
        for args in zip(*its):
            yield func(*args)

    return reader


def shuffle(reader, buf_size):
    """Buffered shuffle: fill a ``buf_size`` window, shuffle it, drain,
    repeat — bounded memory, locally (not globally) shuffled."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """Concatenate readers: all samples of the first, then the second, ..."""

    def reader():
        for r in readers:
            for e in r():
                yield e

    return reader


def compose(*readers, **kwargs):
    """Zip readers into flat tuples: (a, (b, c)) per-sample outputs become
    (a, b, c). ``check_alignment=True`` (default) raises ComposeNotAligned
    when the readers run out at different lengths."""
    check_alignment = kwargs.pop('check_alignment', True)
    if kwargs:
        raise TypeError("compose() got unexpected kwargs %r" % list(kwargs))

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        its = [r() for r in readers]
        done = object()
        while True:
            outs = [next(it, done) for it in its]
            if all(o is done for o in outs):
                return
            if any(o is done for o in outs):
                if check_alignment:
                    raise ComposeNotAligned(
                        "readers yielded different sample counts")
                return
            yield sum((make_tuple(o) for o in outs), ())

    return reader


def buffered(reader, size):
    """Read-ahead on a worker thread through a bounded queue of ``size``
    samples — overlaps producing with consuming."""

    end = object()

    def data_reader():
        q = queue.Queue(maxsize=size)
        err = []
        stop = threading.Event()

        def _post(item):
            # timed put honoring stop: a consumer that abandons the
            # generator mid-stream must not strand the producer in a
            # blocking put on the bounded queue
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for e in reader():
                    if not _post(e):
                        return
            except BaseException as ex:   # surface in the consumer
                err.append(ex)
            finally:
                _post(end)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                # bounded wait (watchdog): the producer posts its sentinel
                # from a finally block, and the liveness probe catches the
                # one remaining hang mode (a producer that died uncleanly)
                if _obs.enabled():
                    # consumer-side starvation signal: how long the
                    # training loop sat waiting on the producer, and how
                    # full the read-ahead buffer is when a sample is taken
                    sw = _obs.Stopwatch()
                    e = bounded_get(q, alive=t.is_alive,
                                    what='buffered reader sample')
                    _obs.histogram('reader.buffered.wait_ms').observe(
                        sw.elapsed_ms())
                    _obs.gauge('reader.buffered.depth').set(q.qsize())
                else:
                    e = bounded_get(q, alive=t.is_alive,
                                    what='buffered reader sample')
                if e is end:
                    if err:
                        raise err[0]
                    return
                yield e
        finally:
            stop.set()
            # the producer sees stop within one put tick; a reader wedged
            # in user code just times the join out rather than hanging
            # consumer teardown
            join_thread(t, timeout=2.0)

    return data_reader


def firstn(reader, n):
    """Limit a reader to its first ``n`` samples."""

    def data_reader():
        return itertools.islice(reader(), n)

    return data_reader


def cache(reader):
    """Materialize the full stream on first iteration; replay from memory
    afterwards (for small datasets with expensive readers). A first fill
    that raises caches nothing, so a retry starts clean."""
    state = {}

    def data_reader():
        if 'data' not in state:
            state['data'] = list(reader())   # only cached when complete
        return iter(state['data'])

    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Map ``mapper`` over a reader with ``process_num`` worker threads and a
    ``buffer_size``-bounded pipeline; ``order=True`` preserves input order.

    Worker threads (not processes): the mappers this decorates are
    numpy/PIL-style transforms that release the GIL, and samples stay in
    shared memory — same overlap the reference gets, minus the pickling.
    """

    end = object()

    def data_reader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)
        err = []

        def feed():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as ex:
                err.append(ex)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            while True:
                item = bounded_get(in_q, alive=threads[0].is_alive,
                                   what='xmap input sample')
                if item is end:
                    out_q.put(end)
                    return
                i, sample = item
                try:
                    out_q.put((i, mapper(sample)))
                except BaseException as ex:
                    err.append(ex)
                    out_q.put(end)
                    return

        threads = [threading.Thread(target=feed, daemon=True)]
        threads += [threading.Thread(target=work, daemon=True)
                    for _ in range(process_num)]
        for t in threads:
            t.start()

        finished = 0
        pending = {}
        next_i = 0
        workers = threads[1:]
        while finished < process_num:
            item = bounded_get(
                out_q, alive=lambda: any(w.is_alive() for w in workers),
                what='xmap mapped sample')
            if item is end:
                finished += 1
                continue
            i, mapped = item
            if not order:
                yield mapped
            else:
                pending[i] = mapped
                while next_i in pending:
                    yield pending.pop(next_i)
                    next_i += 1
        if err:
            raise err[0]

    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Run several readers in forked worker processes, multiplexing their
    samples into one stream (sample order across readers is arbitrary).

    Samples cross the process boundary pickled through a multiprocessing
    queue; use for python-bound readers (parsing, decompression). The
    ``use_pipe`` flag is accepted for API parity — both modes use the
    queue transport here. Requires a fork-capable platform (the worker
    target is a closure, which spawn cannot pickle).
    """
    import multiprocessing as mp

    def data_reader():
        if 'fork' not in mp.get_all_start_methods():
            raise RuntimeError(
                "multiprocess_reader requires the 'fork' start method; "
                "use xmap_readers/buffered on this platform")
        ctx = mp.get_context('fork')
        q = ctx.Queue(queue_size)

        def work(r):
            try:
                for s in r():
                    q.put(('s', s))
            except BaseException as ex:
                q.put(('e', repr(ex)))
            finally:
                q.put(('d', None))

        procs = [ctx.Process(target=work, args=(r,), daemon=True)
                 for r in readers]
        for p in procs:
            p.start()
        done = 0
        try:
            while done < len(procs):
                # liveness-bounded: a worker SIGKILLed mid-sample never
                # posts its 'd' sentinel; without the probe this loop hung
                # forever on q.get()
                kind, payload = bounded_get(
                    q, alive=lambda: any(p.is_alive() for p in procs),
                    what='multiprocess_reader sample')
                if kind == 'd':
                    done += 1
                elif kind == 'e':
                    raise RuntimeError(
                        "multiprocess_reader worker failed: %s" % payload)
                else:
                    yield payload
        finally:
            for p in procs:
                p.join(timeout=1)
                if p.is_alive():
                    p.terminate()

    return data_reader
