"""Recommendation models: Wide&Deep and DeepFM.

Parity: the reference's CTR model zoo (PaddleRec wide_deep / deepfm configs,
trained through fluid parameter-server embeddings — see
python/paddle/fluid/distribute_lookup_table.py and incubate/fleet PS mode).
TPU-first redesign: sparse id features become dense int32 id tensors looked
up in HBM-resident embedding tables (one fused gather feeds the MXU towers);
for vocabularies too big for one chip, shard the tables over the mesh with
distributed.sharding.VocabParallelEmbedding — no parameter server, no async
push/pull.
"""
import numpy as np
import jax.numpy as jnp

from .. import nn
from ..tensor.manipulation import concat
from ..core.tensor import Tensor

__all__ = ['WideDeep', 'DeepFM']


class _SparseEmbeddings(nn.Layer):
    """All sparse slots share ONE [sum(vocabs), dim] table; per-slot ids are
    offset into their vocab range so the whole batch is a single fused
    gather (one HBM read feeding the MXU towers, no per-slot dispatch)."""

    def __init__(self, slot_vocab_sizes, embedding_dim):
        super().__init__()
        offsets = np.concatenate(
            [[0], np.cumsum(slot_vocab_sizes)[:-1]]).astype(np.int32)
        self._offsets = jnp.asarray(offsets)           # [num_slots]
        self.table = nn.Embedding(int(np.sum(slot_vocab_sizes)),
                                  embedding_dim)

    def forward(self, ids):
        # ids: [batch, num_slots] -> [batch, num_slots, dim], one gather
        return self.table(ids + Tensor(self._offsets))


class _MLP(nn.Layer):
    def __init__(self, in_dim, hidden_sizes, act='relu'):
        super().__init__()
        layers = []
        d = in_dim
        for h in hidden_sizes:
            layers.append(nn.Linear(d, h))
            layers.append(nn.ReLU() if act == 'relu' else nn.Sigmoid())
            d = h
        self.net = nn.Sequential(*layers)
        self.out_dim = d

    def forward(self, x):
        return self.net(x)


class WideDeep(nn.Layer):
    """Wide (linear over sparse ids) & Deep (embeddings -> MLP) CTR model.

    Inputs: sparse_ids int [batch, num_slots] (one id per slot; multi-hot
    slots should be pre-pooled), dense_feats float [batch, dense_dim].
    Output: logits [batch, 1] (apply sigmoid for CTR probability).
    """

    def __init__(self, slot_vocab_sizes, dense_dim=13, embedding_dim=16,
                 hidden_sizes=(400, 400, 400)):
        super().__init__()
        self.embeddings = _SparseEmbeddings(slot_vocab_sizes, embedding_dim)
        # wide part: per-slot scalar weights = a fused dim-1 table
        self.wide_tables = _SparseEmbeddings(slot_vocab_sizes, 1)
        self.wide_dense = nn.Linear(dense_dim, 1)
        deep_in = len(slot_vocab_sizes) * embedding_dim + dense_dim
        self.deep = _MLP(deep_in, list(hidden_sizes))
        self.deep_out = nn.Linear(self.deep.out_dim, 1)

    def forward(self, sparse_ids, dense_feats):
        emb = self.embeddings(sparse_ids)                 # [b, s, d]
        deep_in = concat([emb.flatten(1), dense_feats], axis=1)
        deep_logit = self.deep_out(self.deep(deep_in))
        wide_logit = self.wide_dense(dense_feats) + \
            self.wide_tables(sparse_ids).sum(axis=1)      # [b, 1]
        return deep_logit + wide_logit


class DeepFM(nn.Layer):
    """DeepFM: shared embeddings feed an FM 2nd-order term and a deep MLP.

    FM second order uses the (sum^2 - sum-of-squares)/2 identity over the
    slot axis — one fused elementwise reduction, no pairwise loop.
    """

    def __init__(self, slot_vocab_sizes, dense_dim=13, embedding_dim=16,
                 hidden_sizes=(400, 400)):
        super().__init__()
        self.embeddings = _SparseEmbeddings(slot_vocab_sizes, embedding_dim)
        self.first_order = _SparseEmbeddings(slot_vocab_sizes, 1)
        self.dense_first = nn.Linear(dense_dim, 1)
        deep_in = len(slot_vocab_sizes) * embedding_dim + dense_dim
        self.deep = _MLP(deep_in, list(hidden_sizes))
        self.deep_out = nn.Linear(self.deep.out_dim, 1)

    def forward(self, sparse_ids, dense_feats):
        emb = self.embeddings(sparse_ids)                 # [b, s, d]
        # FM 2nd order: 0.5 * ((sum_s e)^2 - sum_s e^2) summed over dim
        sum_emb = emb.sum(axis=1)
        fm2 = ((sum_emb * sum_emb) - (emb * emb).sum(axis=1)) \
            .sum(axis=1, keepdim=True) * 0.5
        fm1 = self.dense_first(dense_feats) + \
            self.first_order(sparse_ids).sum(axis=1)      # [b, 1]
        deep_in = concat([emb.flatten(1), dense_feats], axis=1)
        deep_logit = self.deep_out(self.deep(deep_in))
        return fm1 + fm2 + deep_logit

