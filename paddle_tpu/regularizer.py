"""Parity: python/paddle/fluid/regularizer.py."""
from .nn.regularizer import L1Decay, L2Decay, L1DecayRegularizer, L2DecayRegularizer
