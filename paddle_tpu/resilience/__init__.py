"""paddle_tpu.resilience: fault tolerance for production TPU training.

Four pillars, each independently usable and all threaded through the rest of
the tree (framework.save, hapi.Model.fit, amp.GradScaler, utils.download,
distributed.{env,fs}):

- atomic checkpoint I/O (``atomic_io``, ``CheckpointManager``): temp + fsync
  + os.replace commits, CRC32-stamped manifests, keep-last-N rotation, and
  load-time fallback to the newest non-corrupt checkpoint;
- preemption-safe training (``PreemptionGuard``, hapi ``CheckpointSaver``,
  ``Model.fit(resume_from=...)``): SIGTERM checkpoints before exit, resume
  restores epoch/step, optimizer state, RNG streams, and AMP loss scale for
  bitwise-identical continuation;
- a NaN/Inf step guard (``NanGuard``) that skips poisoned updates and
  reports them to the dynamic GradScaler;
- bounded ``retry`` with exponential backoff + jitter for transient I/O;
- async + sharded + resharding checkpoints (``async_checkpoint``,
  ``CheckpointManager.save(async_=True / sharding= / world=)``): zero-stall
  background commits with a fence, per-rank shard files under a merged CRC
  manifest, and restore onto a *different* mesh shape — the mechanism
  behind the elastic supervisor (docs/RESILIENCE.md, "Elastic training");
- bounded waits + liveness (``watchdog``): ``bounded_get``/``join_thread``/
  ``wait_proc`` and the supervisor ``Heartbeat`` — the primitives behind
  the self-healing DataLoader, the supervised launcher, and collective
  deadlines (graftlint GL012 enforces their use over unbounded stdlib
  waits).

``faultinject`` produces each of the failures above deterministically so the
whole layer is testable on CPU (tier-1, ``-m fault``).
"""
from .atomic_io import (atomic_open, atomic_write, atomic_pickle_dump,
                        crc32_file, crc32_bytes, AtomicWriteError)
from .retry import retry, RetryError
from .preempt import PreemptionGuard
from .nanguard import NanGuard, NanStepError
from .checkpoint import CheckpointManager, capture_rng, restore_rng
from .watchdog import (WatchdogTimeout, bounded_get, join_thread, join_proc,
                       wait_proc, Heartbeat, heartbeat_age)
from . import atomic_io
from . import async_checkpoint
from . import faultinject
from . import watchdog

__all__ = ['atomic_open', 'atomic_write', 'atomic_pickle_dump',
           'crc32_file', 'crc32_bytes',
           'AtomicWriteError', 'retry', 'RetryError', 'PreemptionGuard',
           'NanGuard', 'NanStepError', 'CheckpointManager', 'capture_rng',
           'restore_rng', 'atomic_io', 'async_checkpoint', 'faultinject',
           'watchdog',
           'WatchdogTimeout', 'bounded_get', 'join_thread', 'join_proc',
           'wait_proc', 'Heartbeat', 'heartbeat_age']
