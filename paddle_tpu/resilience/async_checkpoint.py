"""Async snapshots, per-rank sharded checkpoints, and resharding restore.

This module is the mechanism behind elastic training (docs/RESILIENCE.md,
"Elastic training"): checkpoints that (a) never stall the training thread,
(b) are written as per-rank shards so a k-device job writes k small files
instead of one giant one, and (c) can be restored onto a *different* mesh
shape than they were saved on — the surviving ranks of a downsized job load
the dead world's checkpoint and keep training.

Format 2 layout (format 1 is the single-file ``ckpt-<step>.ckpt`` pair in
``checkpoint.py``; ``CheckpointManager`` reads both)::

    <dir>/ckpt_<08d>/shard_rank<R>.npz    # leaf pieces owned by shard rank R
    <dir>/ckpt_<08d>/ready_<R>_<tag>      # zero-byte per-rank commit marker
    <dir>/ckpt_<08d>/extra.pkl            # optional pickled extras (RNG, ...)
    <dir>/ckpt_<08d>/manifest.json        # committed LAST, by rank 0 only

Commit protocol: every shard file and the manifest go through
``atomic_io``; a checkpoint EXISTS only once ``manifest.json`` does, so a
crash (or an injected ENOSPC) partway through a shard write leaves an
*invisible* partial directory and the previous checkpoint untouched. In a
multi-process job each rank writes only its own shard plus a ready marker;
rank 0 waits for every marker (a file barrier — the same run-dir discipline
the supervisor's heartbeats use, watchdog-bounded), CRC32-hashes the shard
files, and commits the manifest. The manifest records every leaf's global
shape/dtype and the byte-exact index range of every piece, so restore can
reassemble the global arrays and re-slice them for ANY target mesh —
sharded→replicated, k→k/2, data×model→data — bitwise-equal to a same-mesh
restore, because the bytes never change, only their placement.

Shard planning comes in two flavors:

- ``config`` (single-process SPMD, the TPU model): pieces are the UNIQUE
  device sub-slices of each leaf under ``ShardingConfig.state_shardings``
  (via ``NamedSharding.devices_indices_map`` — the same math
  ``sharding.shard_shape`` reports bytes with); the owner of a piece is the
  flat mesh position of the first device holding it, so a model-axis
  replica never duplicates bytes into a second file.
- ``world`` (multi-process data-parallel, the spawn/launch model): each
  leaf splits along its first dim divisible by ``world`` (the FSDP
  first-divisible-dim policy; small or indivisible leaves go whole to
  rank 0), and process rank R writes piece R.
"""
import json
import os
import pickle
import shutil
import threading
import time
import zlib

import numpy as np

from .atomic_io import atomic_open, atomic_write, crc32_file
from .watchdog import WatchdogTimeout, join_thread
from .. import observability as _obs

__all__ = ['save_sharded', 'check_sharded', 'load_sharded', 'read_manifest',
           'place_with_config', 'step_dir', 'AsyncSaver', 'AbandonedSave',
           'FORMAT', 'DIR_PREFIX', 'MANIFEST_NAME']

FORMAT = 2
DIR_PREFIX = 'ckpt_'
MANIFEST_NAME = 'manifest.json'
_EXTRA_NAME = 'extra.pkl'
# grace a fence(abandon=True) gives the writer to notice the flag and clean
# up before the fence gives up loudly
_ABANDON_GRACE_S = 5.0


def step_dir(root, step):
    return os.path.join(os.fspath(root), '%s%08d' % (DIR_PREFIX, int(step)))


def _shard_name(rank):
    return 'shard_rank%d.npz' % int(rank)


class AbandonedSave(Exception):
    """An in-flight save was cooperatively abandoned (preemption fence):
    its uncommitted artifacts were removed; no checkpoint was written."""


# ---------------------------------------------------------------------------
# pytree <-> manifest
# ---------------------------------------------------------------------------

def _is_array(x):
    return hasattr(x, 'shape') and hasattr(x, 'dtype')


def _unwrap(x):
    """Tensor -> raw array; everything else passes through."""
    return getattr(x, '_value', x)


def _flatten(tree):
    """(json treedef, [leaf, ...]) over dict/list/tuple nesting. Array
    leaves become ``{'__leaf__': i}``; plain scalars/None inline."""
    leaves = []

    def walk(node):
        node = _unwrap(node)
        if isinstance(node, dict):
            return {'__dict__': {str(k): walk(v) for k, v in node.items()}}
        if isinstance(node, (list, tuple)):
            key = '__list__' if isinstance(node, list) else '__tuple__'
            return {key: [walk(v) for v in node]}
        if node is None or isinstance(node, (bool, int, float, str)):
            return {'__value__': node}
        leaves.append(node)
        return {'__leaf__': len(leaves) - 1}

    return walk(tree), leaves


def _unflatten(treedef, leaves):
    def walk(node):
        if '__dict__' in node:
            return {k: walk(v) for k, v in node['__dict__'].items()}
        if '__list__' in node:
            return [walk(v) for v in node['__list__']]
        if '__tuple__' in node:
            return tuple(walk(v) for v in node['__tuple__'])
        if '__value__' in node:
            return node['__value__']
        return leaves[node['__leaf__']]

    return walk(treedef)


def _map_leaves(tree, fn):
    """Structure-preserving map over the same nesting _flatten walks (used
    for the donation-safe device-side copy — jax.tree_map would recurse
    into Tensor registrations this module must not assume)."""
    if isinstance(tree, dict):
        return {k: _map_leaves(v, fn) for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        t = type(tree)
        return t(_map_leaves(v, fn) for v in tree)
    return fn(tree)


def _tree_get(tree, path):
    node = tree
    for part in path:
        node = node[part]
    return node


# ---------------------------------------------------------------------------
# shard planning
# ---------------------------------------------------------------------------

def _norm_index(idx, shape):
    """A device index (tuple of slices) as ``[[start, stop], ...]``."""
    out = []
    for d, dim in enumerate(shape):
        sl = idx[d] if d < len(idx) else slice(None)
        start = 0 if sl.start is None else int(sl.start)
        stop = int(dim) if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _leaf_paths(tree):
    """[(path tuple, leaf), ...] in _flatten's walk order."""
    out = []

    def walk(node, path):
        node = _unwrap(node)
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, path + (str(k),))
            return
        if isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(v, path + (i,))
            return
        if node is None or isinstance(node, (bool, int, float, str)):
            return
        out.append((path, node))

    walk(tree, ())
    return out


def _sharded_dim(pieces):
    """The dim along which the pieces differ (None when single-piece)."""
    if len(pieces) <= 1:
        return None
    first = pieces[0]['index']
    for d in range(len(first)):
        if any(p['index'][d] != first[d] for p in pieces[1:]):
            return d
    return None


def _plan_config(state, config):
    """Per-leaf piece plans from a ``ShardingConfig``: unique device
    sub-slices, owner = flat mesh position of the first holder."""
    shardings = config.state_shardings(state)
    flat_devs = list(np.asarray(config.mesh.devices).flat)
    pos_of = {id(d): i for i, d in enumerate(flat_devs)}
    plans = []
    for n, (path, leaf) in enumerate(_leaf_paths(state)):
        shape = tuple(int(s) for s in leaf.shape)
        sharding = _tree_get(shardings, path)
        pieces = []
        seen = {}
        try:
            idx_map = sharding.devices_indices_map(shape)
        except Exception:
            idx_map = {}
        if idx_map:
            for dev in flat_devs:
                idx = idx_map.get(dev)
                if idx is None:
                    continue
                norm = _norm_index(idx, shape)
                key = tuple(map(tuple, norm))
                if key not in seen:
                    seen[key] = True
                    pieces.append({'rank': pos_of[id(dev)], 'index': norm})
        if not pieces:
            pieces = [{'rank': 0,
                       'index': [[0, d] for d in shape]}]
        plans.append({'path': list(path), 'key': 'L%05d' % n,
                      'shape': list(shape), 'dtype': str(leaf.dtype),
                      'dim': _sharded_dim(pieces), 'pieces': pieces})
    return plans, len(flat_devs)


def _split_dim(shape, world, min_size):
    """The canonical FSDP first-divisible-dim policy as a dim index (the
    ONE implementation, ``distributed.sharding.first_divisible_spec`` —
    tools/ckpt.py mirrors it stdlib-only by documented exception)."""
    from ..distributed.sharding import first_divisible_spec
    spec = first_divisible_spec(shape, world, '_ckpt_', min_size)
    for d, part in enumerate(spec):
        if part is not None:
            return d
    return None


def _plan_world(state, world, min_size=1024):
    """Per-leaf piece plans for ``world`` process ranks: the FSDP
    first-divisible-dim split (indivisible or small leaves go whole to
    rank 0)."""
    world = max(int(world), 1)
    plans = []
    for n, (path, leaf) in enumerate(_leaf_paths(state)):
        shape = tuple(int(s) for s in leaf.shape)
        dim = _split_dim(shape, world, min_size) if world > 1 else None
        if dim is None:
            pieces = [{'rank': 0, 'index': [[0, d] for d in shape]}]
        else:
            chunk = shape[dim] // world
            pieces = []
            for r in range(world):
                index = [[0, d] for d in shape]
                index[dim] = [r * chunk, (r + 1) * chunk]
                pieces.append({'rank': r, 'index': index})
        plans.append({'path': list(path), 'key': 'L%05d' % n,
                      'shape': list(shape), 'dtype': str(leaf.dtype),
                      'dim': dim, 'pieces': pieces})
    return plans


def _piece_arrays(leaf, plan, want_ranks):
    """Host (numpy) arrays for this leaf's pieces owned by ``want_ranks``:
    ``{piece_i: ndarray}``. Prefers a jax array's addressable shards (no
    global gather) and falls back to one host copy + slicing."""
    wanted = {i: p for i, p in enumerate(plan['pieces'])
              if p['rank'] in want_ranks}
    if not wanted:
        return {}
    out = {}
    shape = tuple(plan['shape'])
    shards = getattr(leaf, 'addressable_shards', None)
    if shards:
        by_index = {}
        for sh in shards:
            try:
                key = tuple(map(tuple, _norm_index(sh.index, shape)))
            # a shard whose index cannot be normalized is simply not used
            # as a fast path — the one-host-copy fallback below covers it
            except Exception:   # graftlint: disable=GL019
                continue
            if key not in by_index:
                by_index[key] = sh.data
        for i, p in wanted.items():
            key = tuple(map(tuple, p['index']))
            if key in by_index:
                out[i] = np.asarray(by_index[key])
    missing = [i for i in wanted if i not in out]
    if missing:
        arr = np.asarray(leaf)   # device->host (or identity for numpy)
        for i in missing:
            sl = tuple(slice(s, e) for s, e in wanted[i]['index'])
            out[i] = arr[sl] if sl else arr
    return out


# ---------------------------------------------------------------------------
# write / verify / read
# ---------------------------------------------------------------------------

class _AbortCheckingStream:
    """File proxy raising ``AbandonedSave`` between writes once the
    cooperative abandon flag flips — keeps a fence responsive even while a
    single large (or fault-slowed) shard file is streaming."""

    def __init__(self, f, should_abort):
        self._f = f
        self._should_abort = should_abort

    def write(self, data):
        if self._should_abort():
            raise AbandonedSave('save abandoned mid-stream')
        return self._f.write(data)

    def __getattr__(self, name):
        return getattr(self._f, name)


class _ShardStream:
    """WRITE-ONLY stream for shard files: accumulates CRC32 + byte count
    as the zip streams (no read-back of a multi-GB shard after commit —
    the same discipline as checkpoint.py's ``_Crc32Writer``) and checks
    the cooperative abandon flag per write. Deliberately exposes no
    seek/tell: zipfile then treats the stream as unseekable and emits
    data descriptors instead of seeking back to patch headers — which is
    exactly what makes a linear CRC correct (np.load reads both forms)."""

    def __init__(self, f, should_abort=None):
        self._f = f
        self._should_abort = should_abort
        self.crc = 0
        self.size = 0

    def write(self, data):
        if self._should_abort is not None and self._should_abort():
            raise AbandonedSave('save abandoned mid-stream')
        self.crc = zlib.crc32(data, self.crc) & 0xFFFFFFFF
        self.size += len(data)
        return self._f.write(data)

    def flush(self):
        self._f.flush()


def _write_shard(dirpath, rank, arrays, should_abort=None):
    """One shard file in the npz (zip of .npy members) format, written
    member-by-member so a failing stream is torn down deterministically
    (np.savez's internal ZipFile would otherwise complain from __del__
    after an injected ENOSPC closes the staged file under it). Returns
    ``(path, crc32, size)`` accumulated while streaming."""
    import zipfile
    path = os.path.join(dirpath, _shard_name(rank))
    with atomic_open(path) as f:
        w = _ShardStream(f, should_abort)
        zf = zipfile.ZipFile(w, 'w', zipfile.ZIP_STORED, allowZip64=True)
        try:
            for name, arr in arrays.items():
                with zf.open(name + '.npy', 'w', force_zip64=True) as zm:
                    np.lib.format.write_array(zm, np.asarray(arr))
        finally:
            try:
                zf.close()
            except Exception:
                pass   # the stream already failed; atomic_open cleans up
    return path, w.crc, w.size


def _marker(dirpath, rank, tag):
    return os.path.join(dirpath, 'ready_%d_%s' % (int(rank), tag))


def _wait_markers(dirpath, nranks, tag, timeout, tick=0.05):
    """Rank 0's commit barrier: every rank's ready marker for THIS tag
    (generation) must exist before the manifest hashes the shard files —
    a stale shard from a previous generation must never be committed."""
    deadline = time.monotonic() + float(timeout)
    missing = list(range(nranks))
    while True:
        missing = [r for r in missing
                   if not os.path.exists(_marker(dirpath, r, tag))]
        if not missing:
            return
        if time.monotonic() >= deadline:
            raise WatchdogTimeout(
                "sharded checkpoint barrier: ranks %s never committed "
                "their shard (tag %s) within %.1fs — dead or wedged peers; "
                "the manifest was NOT written and this step stays "
                "invisible" % (missing, tag, timeout),
                what='checkpoint shard barrier', waited=float(timeout))
        time.sleep(tick)


def _default_tag():
    return os.environ.get('PADDLE_TPU_ELASTIC_GENERATION', '0') or '0'


def save_sharded(root, state, step, meta=None, config=None, world=None,
                 rank=None, tag=None, extra=None, barrier_timeout=60.0,
                 should_abort=None, min_size=1024):
    """Commit ``state`` as sharded checkpoint ``step`` under ``root``.

    ``config``: a ``distributed.ShardingConfig`` — pieces follow
    ``state_shardings`` (single-process SPMD). ``world``/``rank``: the
    multi-process split — with ``rank=None`` every shard is written by this
    process; with ``rank=R`` only R's shard (plus, on rank 0, the barrier
    wait and the manifest commit). Returns the manifest dict, or None for
    non-committing ranks / an abandoned save.
    """
    d = step_dir(root, step)
    os.makedirs(d, exist_ok=True)
    tag = str(tag) if tag is not None else _default_tag()
    should_abort = should_abort or (lambda: False)
    if config is not None:
        plans, nranks = _plan_config(state, config)
        mesh_desc = {'axes': dict(config.mesh.shape),
                     'fsdp': bool(config.fsdp),
                     'tensor_parallel_degree':
                         int(config.tensor_parallel_degree)}
    else:
        nranks = max(int(world or 1), 1)
        plans = _plan_world(state, nranks, min_size=min_size)
        mesh_desc = None
    treedef, leaves = _flatten(state)
    my_ranks = list(range(nranks)) if rank is None else [int(rank)]
    try:
        per_rank = {r: {} for r in my_ranks}
        want = set(my_ranks)
        for plan, leaf in zip(plans, leaves):
            if should_abort():
                raise AbandonedSave('save abandoned before shard build')
            for i, arr in _piece_arrays(leaf, plan, want).items():
                piece = plan['pieces'][i]
                per_rank[piece['rank']]['%s.p%d' % (plan['key'], i)] = arr
        streamed = {}
        for r in my_ranks:
            if should_abort():
                raise AbandonedSave('save abandoned between shards')
            _p, crc, size = _write_shard(d, r, per_rank[r], should_abort)
            streamed[r] = {'file': _shard_name(r), 'size': size,
                           'crc32': crc}
            with open(_marker(d, r, tag), 'w'):   # atomic-ok: 0-byte marker
                pass
        if rank is not None and int(rank) != 0:
            return None
        if rank is not None:
            _wait_markers(d, nranks, tag, barrier_timeout)
        if should_abort():
            raise AbandonedSave('save abandoned before manifest commit')
        shards = {}
        for r in range(nranks):
            if r in streamed:
                # this process wrote it: CRC/size accumulated while
                # streaming — no read-back of a multi-GB shard
                shards[str(r)] = streamed[r]
            else:
                # a peer's shard (rank-0 barrier commit): read-back is the
                # only way to stamp bytes this process never saw
                p = os.path.join(d, _shard_name(r))
                shards[str(r)] = {'file': _shard_name(r),
                                  'size': os.path.getsize(p),
                                  'crc32': crc32_file(p)}
        extra_entry = None
        if extra is not None:
            ep = os.path.join(d, _EXTRA_NAME)
            with atomic_open(ep) as f:
                w = _ShardStream(f, should_abort)
                pickle.dump(extra, w, protocol=4)
            extra_entry = {'file': _EXTRA_NAME,
                           'size': w.size, 'crc32': w.crc}
        manifest = {'format': FORMAT, 'step': int(step), 'world': nranks,
                    'mesh': mesh_desc, 'tag': tag, 'meta': dict(meta or {}),
                    'shards': shards, 'extra': extra_entry,
                    'leaves': plans, 'treedef': treedef}
        atomic_write(os.path.join(d, MANIFEST_NAME),
                     json.dumps(manifest, sort_keys=True).encode())
        return manifest
    except AbandonedSave:
        _cleanup_uncommitted(d, my_ranks, tag, whole_dir=rank is None)
        if _obs.enabled():
            _obs.event('checkpoint.abandoned', step=int(step))
        return None
    except BaseException:
        # a failed save (ENOSPC, injected fault, ...) must leave nothing
        # that LOOKS like a checkpoint: without a manifest the step is
        # invisible either way, but the husk is removed so operators (and
        # tests) see a clean directory. Multi-process ranks remove only
        # their OWN artifacts — siblings may still be writing theirs.
        _cleanup_uncommitted(d, my_ranks, tag, whole_dir=rank is None)
        raise


def _cleanup_uncommitted(d, ranks, tag, whole_dir):
    """Remove a failed/abandoned save's artifacts — but ONLY when the
    directory holds no committed manifest (a prior committed step
    re-targeted by an aborted overwrite keeps whatever it had; its CRCs
    decide at load)."""
    if os.path.exists(os.path.join(d, MANIFEST_NAME)):
        return
    if whole_dir:
        shutil.rmtree(d, ignore_errors=True)
        return
    for r in ranks:
        for p in (os.path.join(d, _shard_name(r)), _marker(d, r, tag)):
            try:
                os.unlink(p)
            except OSError:
                pass


def read_manifest(dirpath):
    with open(os.path.join(dirpath, MANIFEST_NAME), 'rb') as f:
        return json.loads(f.read().decode())


def check_sharded(dirpath):
    """None when the checkpoint dir is intact, else a defect description.
    Validates the manifest and every shard/extra file's size + CRC32
    BEFORE any array bytes are deserialized."""
    try:
        man = read_manifest(dirpath)
    except (OSError, ValueError) as e:
        return 'unreadable manifest (%s)' % e
    if man.get('format') != FORMAT:
        return 'unknown manifest format %r' % man.get('format')
    entries = list(man.get('shards', {}).values())
    if man.get('extra'):
        entries.append(man['extra'])
    for ent in entries:
        p = os.path.join(dirpath, ent['file'])
        if not os.path.isfile(p):
            return 'shard %s missing' % ent['file']
        size = os.path.getsize(p)
        if size != ent.get('size'):
            return 'shard %s truncated/resized (%d bytes, manifest says ' \
                '%s)' % (ent['file'], size, ent.get('size'))
        crc = crc32_file(p)
        if crc != ent.get('crc32'):
            return 'shard %s CRC32 mismatch (0x%08x, manifest says ' \
                '0x%08x)' % (ent['file'], crc, ent.get('crc32', 0))
    return None


def load_sharded(dirpath, return_extra=False):
    """Reassemble the host (numpy) state of a committed sharded checkpoint.

    The caller is expected to have run :func:`check_sharded` first (the
    ``CheckpointManager`` does); this only reads. Returns ``(state, meta)``
    or ``(state, meta, extra)``."""
    man = read_manifest(dirpath)
    npz = {}

    def shard(r):
        if r not in npz:
            npz[r] = np.load(os.path.join(dirpath, _shard_name(r)),
                             allow_pickle=False)
        return npz[r]

    leaves = []
    for plan in man['leaves']:
        shape = tuple(plan['shape'])
        pieces = plan['pieces']
        if len(pieces) == 1:
            arr = shard(pieces[0]['rank'])['%s.p0' % plan['key']]
            leaves.append(np.asarray(arr).reshape(shape))
            continue
        out = np.empty(shape, dtype=np.dtype(plan['dtype']))
        for i, piece in enumerate(pieces):
            sl = tuple(slice(s, e) for s, e in piece['index'])
            out[sl] = shard(piece['rank'])['%s.p%d' % (plan['key'], i)]
        leaves.append(out)
    for f in npz.values():
        f.close()
    state = _unflatten(man['treedef'], leaves)
    meta = dict(man.get('meta') or {})
    if not return_extra:
        return state, meta
    extra = None
    if man.get('extra'):
        with open(os.path.join(dirpath, man['extra']['file']), 'rb') as f:
            extra = pickle.load(f)
    return state, meta, extra


def place_with_config(state, config):
    """Reshard a host engine-state pytree onto ``config``'s mesh: the
    resharding-restore placement (``None`` config returns the host state).
    The tree must be engine-layout (``params``/``buffers``/``opt``[...]) —
    that is what ``state_shardings`` describes."""
    if config is None:
        return state
    if not (isinstance(state, dict) and 'params' in state):
        got = sorted(state) if isinstance(state, dict) else type(state)
        raise ValueError(
            "resharding restore needs an engine-layout state "
            "({'params', 'buffers', 'opt', ...}) — got %r" % (got,))
    shardings = config.state_shardings(state)
    return config.device_put_state(state, shardings)


# ---------------------------------------------------------------------------
# the async saver
# ---------------------------------------------------------------------------

def secure_for_async(state):
    """Donation-safe leaf capture for a background save: on backends that
    honor buffer donation the step about to run would invalidate the very
    buffers the snapshot references, so take cheap device-side copies
    first (an async enqueue, not a host transfer). Everywhere else (CPU:
    donation ignored, arrays immutable) this is a no-op."""
    try:
        from ..engine.builder import donation_supported
        if not donation_supported():
            return state
        import copy as _copy
        import jax
        import jax.numpy as jnp

        def copy_leaf(x):
            if isinstance(x, jax.Array):
                return jnp.copy(x)
            inner = getattr(x, '_value', None)
            if isinstance(inner, jax.Array):
                # Tensor-wrapped leaf: keep the wrapper (name/Parameter-ness
                # matter to the serializer), copy only the device buffer
                dup = _copy.copy(x)
                dup._value = jnp.copy(inner)
                return dup
            return x

        return _map_leaves(state, copy_leaf)
    except Exception:
        return state


class AsyncSaver:
    """ONE in-flight background save, with a fence on the next.

    ``submit(job)`` runs ``job(should_abort)`` on a daemon thread; the
    *caller* is expected to have fenced first (``CheckpointManager.save``
    does). ``fence()`` blocks (watchdog-bounded ticks) until the in-flight
    save finishes; ``fence(abandon=True, timeout=t)`` flips the
    cooperative abandon flag after ``t`` seconds so the writer stops at
    its next write boundary and removes its uncommitted artifacts — the
    preemption contract: an async save racing a SIGTERM either finishes
    or cleanly vanishes before the preemption checkpoint starts. A
    worker-thread failure is re-raised on the next ``submit``/``fence``.
    """

    def __init__(self, name='paddle-tpu-async-ckpt'):
        self._name = name
        self._thread = None
        self._error = None
        self._abandon = False

    def in_flight(self):
        return self._thread is not None and self._thread.is_alive()

    def _raise_pending(self):
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def submit(self, job):
        self.fence()
        self._abandon = False

        def run():
            try:
                job(lambda: self._abandon)
            except AbandonedSave:
                pass
            except BaseException as e:   # surfaced on the next save/fence
                self._error = e
                if _obs.enabled():
                    _obs.counter('checkpoint.async_errors').inc()
                    _obs.event('checkpoint.async_error', error=repr(e))

        self._thread = threading.Thread(target=run, name=self._name,
                                        daemon=True)
        self._thread.start()

    def fence(self, timeout=None, abandon=False):
        """Wait for the in-flight save. Returns the milliseconds this
        caller was blocked (0.0 when nothing was in flight)."""
        t = self._thread
        waited_ms = 0.0
        if t is not None and t.is_alive():
            sw = _obs.Stopwatch()
            done = join_thread(t, timeout=timeout)
            if not done and abandon:
                self._abandon = True
                done = join_thread(t, timeout=_ABANDON_GRACE_S)
            waited_ms = sw.elapsed_ms()
            if not done:
                raise WatchdogTimeout(
                    "async checkpoint fence: the in-flight save did not "
                    "finish%s within %.1fs — wedged filesystem?"
                    % (' (or abandon)' if abandon else '',
                       (timeout or 0) + (_ABANDON_GRACE_S if abandon
                                         else 0)),
                    what='async checkpoint fence', waited=waited_ms / 1e3)
            if _obs.enabled():
                _obs.event('checkpoint.fence',
                           waited_ms=round(waited_ms, 3),
                           abandoned=bool(abandon and self._abandon))
        self._thread = None
        self._raise_pending()
        return waited_ms
