"""Atomic file I/O: the single write path every checkpoint byte goes through.

Rule (enforced by graftlint GL010, docs/ANALYSIS.md): checkpoint-shaped code never
opens its final destination for writing. It stages bytes in a same-directory
temp file, fsyncs, and commits with ``os.replace`` — so a reader observes
either the old complete file or the new complete file, never a torn one.
POSIX guarantees rename atomicity only within a filesystem, hence the
same-directory temp (cross-device rename would fall back to copy+delete).

Stdlib-only on purpose: framework.py imports this before the jax backend is
up, and utils/hermetic.py-style early loaders must be able to pull it in
without touching the package __init__.
"""
import contextlib
import itertools
import os
import pickle
import shutil
import threading
import zlib

__all__ = ['atomic_open', 'atomic_write', 'atomic_pickle_dump', 'crc32_file',
           'crc32_bytes', 'AtomicWriteError']

# Fault-injection seam (resilience/faultinject.py): called as
# hook(stage, path) with stage in {'write', 'replace'}; raising here models a
# crash at that point of the commit protocol. None in production.
_fault_hook = None

# Stream-level fault seam: called as hook(path, bytes_so_far, chunk_len)
# BEFORE every staged write() once armed — raising models ENOSPC partway
# through a payload (faultinject.disk_full), sleeping models a slow
# filesystem (faultinject.slow_fs). None in production: the wrapper below is
# only interposed while a hook is armed, so the hot path stays a bare file.
_stream_hook = None


class AtomicWriteError(OSError):
    """A staged write failed before commit; the destination is untouched."""


def _invoke_hook(stage, path):
    if _fault_hook is not None:
        _fault_hook(stage, path)


# per-call temp-name uniquifier: pid alone is not enough — two threads of one
# process writing the same destination (async checkpointer racing a shutdown
# save) must never share a staging file
_tmp_seq = itertools.count()


@contextlib.contextmanager
def atomic_open(path, fsync=True):
    """Context manager: a writable binary stream whose contents land on
    ``path`` atomically at clean exit.

    Stages into a ``.<name>.tmp.<pid>.<tid>.<seq>`` sibling in the
    destination directory, fsyncs the payload, then ``os.replace``s over the
    final name and fsyncs the directory entry so the rename itself survives
    power loss. On any failure the temp file is removed and the destination
    keeps its previous contents. Streaming writers (pickle.dump, large
    copies) use this directly so nothing is materialized in memory.
    """
    path = os.fspath(path)
    d = os.path.dirname(path) or '.'
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, '.%s.tmp.%d.%d.%d' % (
        os.path.basename(path), os.getpid(), threading.get_ident(),
        next(_tmp_seq)))
    try:
        _invoke_hook('write', path)
        f = open(tmp, 'wb')   # atomic-ok: staged temp, committed below
        try:
            yield (f if _stream_hook is None
                   else _HookedStream(f, path, _stream_hook))
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        finally:
            f.close()
        _invoke_hook('replace', path)
        os.replace(tmp, path)
    except BaseException as e:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        if isinstance(e, (OSError, IOError)) and \
                not isinstance(e, AtomicWriteError):
            raise AtomicWriteError(
                "atomic write to %r failed before commit (%s); the "
                "destination was left untouched" % (path, e)) from e
        raise
    if fsync:
        _fsync_dir(d)


class _HookedStream:
    """File proxy interposed only while a stream fault hook is armed:
    forwards everything (seek/tell/fileno — zipfile/np.savez need them) but
    routes ``write`` through the hook with a running byte count, so an
    injector can fail or delay a commit *partway through* the payload."""

    def __init__(self, f, path, hook):
        self._f = f
        self._path = path
        self._hook = hook
        self._written = 0

    def write(self, data):
        self._hook(self._path, self._written, len(data))
        n = self._f.write(data)
        self._written += len(data)
        return n

    def __getattr__(self, name):
        return getattr(self._f, name)


def atomic_write(path, data, fsync=True):
    """Write ``data`` (bytes, or a readable file-like streamed in 1 MiB
    chunks) to ``path`` through the :func:`atomic_open` commit protocol."""
    with atomic_open(path, fsync=fsync) as f:
        if hasattr(data, 'read'):
            shutil.copyfileobj(data, f, length=1 << 20)
        else:
            f.write(data)
    return path


def _fsync_dir(d):
    """Persist a directory entry (the rename) — best-effort on filesystems
    that reject directory fds."""
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_pickle_dump(obj, path, protocol=4, fsync=True):
    """Pickle ``obj`` to ``path`` through the atomic commit protocol.

    Streams via pickle.dump into the staged temp (same peak memory as the
    pre-resilience bare-open path — no full serialized blob in RAM)."""
    with atomic_open(path, fsync=fsync) as f:
        pickle.dump(obj, f, protocol=protocol)
    return path


def crc32_bytes(data):
    return zlib.crc32(data) & 0xFFFFFFFF


def crc32_file(path, chunk=1 << 20):
    crc = 0
    with open(path, 'rb') as f:
        for block in iter(lambda: f.read(chunk), b''):
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF
