"""CRC-stamped, rotating, fallback-capable checkpoint store.

Two on-disk formats under one directory (both may coexist; ``steps()`` /
``load()`` / ``restore()`` see the union):

Format 1 — single-file::

    ckpt-00000012.ckpt            # pickled payload (Tensors -> numpy)
    ckpt-00000012.manifest.json   # {"format":1,"step":12,"size":...,"crc32":...,
                                  #  "meta":{"epoch":3,"step_in_epoch":0,...}}

Format 2 — sharded (``async_checkpoint``; docs/RESILIENCE.md, "Elastic
training")::

    ckpt_00000012/shard_rank<R>.npz   # per-rank leaf pieces
    ckpt_00000012/manifest.json       # merged CRC manifest, committed LAST

Commit protocol (both formats): payload/shards first, manifest second, all
through ``atomic_io.atomic_write``. A checkpoint EXISTS only once its
manifest does; a crash between the writes leaves invisible orphans that the
next save of that step overwrites. ``load()``/``restore()`` walk steps
newest first, verify size+CRC32 against the manifest, and transparently
fall back to the newest non-corrupt checkpoint (warning on every skip) — a
torn or bit-flipped latest file costs one checkpoint interval, not the run.

``save(state, async_=True)`` snapshots and commits on a background thread
(ONE in flight; the next save — or an explicit :meth:`fence` — waits for
it), recording ``checkpoint.save_stall_ms`` (training-thread blocked time)
separately from ``checkpoint.commit_ms`` (total commit latency): in steady
state the stall is ~0 while the commit runs as long as the disk needs.
``save(state, sharding=cfg)`` / ``save(state, world=W, rank=R)`` write the
sharded format; ``restore(sharding=new_cfg)`` reassembles any committed
checkpoint and re-places it onto a *different* mesh (resharding restore).
"""
import json
import os
import pickle
import warnings
import zlib

from .atomic_io import atomic_open, atomic_write, crc32_file
from .. import observability as _obs

__all__ = ['CheckpointManager', 'capture_rng', 'restore_rng']

_FMT = 1
_PREFIX = 'ckpt-'
_PAYLOAD_EXT = '.ckpt'
_MANIFEST_EXT = '.manifest.json'
_V2_PREFIX = 'ckpt_'


class CheckpointManager:
    """Keep-last-N rotating checkpoint directory with corruption fallback,
    async (background-thread) saves, per-rank sharded checkpoints, and
    resharding restore."""

    def __init__(self, path, max_keep=3):
        self.path = os.fspath(path)
        self.max_keep = max_keep
        self._async = None   # lazy AsyncSaver (one in-flight save)

    # -- naming -------------------------------------------------------------
    def _payload(self, step):
        return os.path.join(self.path, '%s%08d%s' % (_PREFIX, step,
                                                     _PAYLOAD_EXT))

    def _manifest(self, step):
        return os.path.join(self.path, '%s%08d%s' % (_PREFIX, step,
                                                     _MANIFEST_EXT))

    def _v2_dir(self, step):
        return os.path.join(self.path, '%s%08d' % (_V2_PREFIX, int(step)))

    def _is_v2(self, step):
        from . import async_checkpoint as ac
        return os.path.isfile(os.path.join(self._v2_dir(step),
                                           ac.MANIFEST_NAME))

    def steps(self):
        """Committed (manifest present) steps, ascending — both formats."""
        if not os.path.isdir(self.path):
            return []
        out = set()
        for name in os.listdir(self.path):
            if name.startswith(_PREFIX) and name.endswith(_MANIFEST_EXT):
                digits = name[len(_PREFIX):-len(_MANIFEST_EXT)]
                if digits.isdigit():
                    out.add(int(digits))
            elif name.startswith(_V2_PREFIX):
                digits = name[len(_V2_PREFIX):]
                if digits.isdigit() and os.path.isfile(
                        os.path.join(self.path, name, 'manifest.json')):
                    out.add(int(digits))
        return sorted(out)

    def latest_step(self):
        s = self.steps()
        return s[-1] if s else None

    # -- async machinery ----------------------------------------------------
    def _saver(self):
        if self._async is None:
            from .async_checkpoint import AsyncSaver
            self._async = AsyncSaver()
        return self._async

    def in_flight(self):
        """True while a background save is still committing."""
        return self._async is not None and self._async.in_flight()

    def fence(self, timeout=None, abandon=False):
        """Block until the in-flight async save (if any) finishes; with
        ``abandon=True`` a save still running after ``timeout`` seconds is
        cooperatively abandoned (it removes its uncommitted artifacts) —
        the contract the preemption checkpoint relies on. Re-raises a
        background save's failure. Returns blocked milliseconds."""
        if self._async is None:
            return 0.0
        return self._async.fence(timeout=timeout, abandon=abandon)

    # -- write --------------------------------------------------------------
    def save(self, state, step=None, meta=None, *, async_=False,
             sharding=None, world=None, rank=None, tag=None, extra=None):
        """Atomically commit ``state`` as checkpoint ``step``
        (default: latest+1).

        - ``async_=True``: snapshot (device->host) + serialization + commit
          run on a background thread; this call returns after fencing any
          previous in-flight save (ONE save in flight) and records only
          the training-thread stall. On donating backends the leaves are
          first secured with cheap device-side copies.
        - ``sharding=`` (a ``distributed.ShardingConfig``): sharded format —
          one ``shard_rank<R>.npz`` per mesh position plus a merged CRC
          manifest (see ``async_checkpoint``).
        - ``world=``/``rank=``: the multi-process sharded split — each rank
          writes only its shard; rank 0 commits the manifest after the
          shard barrier.
        - ``extra=``: small pickled side payload (RNG streams, loop
          position) stored next to the shards and CRC'd in the manifest.
        """
        from ..framework import _to_saveable
        sw = _obs.Stopwatch()
        # ordering fence FIRST: a save must never land after a LATER one —
        # and the default step number must see the in-flight commit, or
        # back-to-back async saves with step=None would both read the same
        # latest_step() and silently overwrite each other
        self.fence()
        if step is None:
            latest = self.latest_step()
            step = 0 if latest is None else latest + 1
        step = int(step)
        sharded = sharding is not None or world is not None \
            or rank is not None
        if extra is not None and not sharded:
            # the side payload (RNG streams, loop position) only exists in
            # the sharded manifest format — promote rather than drop it
            sharded, world = True, 1
        committer = rank is None or int(rank) == 0
        meta = dict(meta or {})

        if sharded:
            from . import async_checkpoint as ac
            if sharding is not None:
                from ..distributed.strategy import resolve_sharding
                sharding = resolve_sharding(sharding)
            src = ac.secure_for_async(state) if async_ else state

            def job(should_abort):
                jsw = _obs.Stopwatch()
                man = ac.save_sharded(
                    self.path, src, step, meta=meta, config=sharding,
                    world=world, rank=rank, tag=tag, extra=extra,
                    should_abort=should_abort)
                if man is not None:
                    nbytes = sum(s['size'] for s in man['shards'].values())
                    self._finish_commit(step, jsw, meta, nbytes,
                                        async_=async_, sharded=True)
                if committer:
                    self._rotate()
        else:
            src = state
            if async_:
                from . import async_checkpoint as ac
                src = ac.secure_for_async(state)

            def job(should_abort):
                from . import async_checkpoint as ac
                jsw = _obs.Stopwatch()
                pay_path = self._payload(step)
                try:
                    with atomic_open(pay_path) as f:
                        if should_abort is not None:
                            f = ac._AbortCheckingStream(f, should_abort)
                        w = _Crc32Writer(f)
                        # streamed: no full blob in RAM; CRC/size accumulate
                        # while writing — no read-back of a multi-GB payload
                        # inside the preemption grace window
                        pickle.dump(_to_saveable(src), w, protocol=4)
                except ac.AbandonedSave:
                    if _obs.enabled():
                        _obs.event('checkpoint.abandoned', step=step)
                    return
                manifest = {'format': _FMT, 'step': step, 'size': w.size,
                            'crc32': w.crc, 'meta': meta}
                atomic_write(self._manifest(step),
                             json.dumps(manifest, sort_keys=True).encode())
                self._finish_commit(step, jsw, meta, w.size,
                                    async_=async_, sharded=False)
                self._rotate()

        if async_:
            self._saver().submit(job)
        else:
            job(lambda: False)
        if _obs.enabled():
            stall = sw.elapsed_ms()
            _obs.histogram('checkpoint.save_stall_ms').observe(stall)
        return step

    def _finish_commit(self, step, sw, meta, nbytes, async_, sharded):
        """Telemetry at manifest-commit time (runs on the writer thread
        for async saves)."""
        if not _obs.enabled():
            return
        ms = sw.elapsed_ms()
        _obs.histogram('checkpoint.commit_ms').observe(ms)
        # legacy name: the pre-async save duration histogram
        _obs.histogram('checkpoint.save_ms').observe(ms)
        _obs.counter('checkpoint.saves').inc()
        _obs.event('checkpoint.save', step=step, bytes=nbytes,
                   duration_ms=round(ms, 3), async_=bool(async_),
                   sharded=bool(sharded), meta=meta)

    def _rotate(self):
        if not self.max_keep:
            return
        import shutil
        for s in self.steps()[:-self.max_keep]:
            for p in (self._payload(s), self._manifest(s)):
                try:
                    os.unlink(p)
                except OSError:
                    pass
            shutil.rmtree(self._v2_dir(s), ignore_errors=True)

    # -- read ---------------------------------------------------------------
    def verify(self, step):
        """True iff checkpoint ``step``'s payload matches its manifest."""
        return self._check(step) is None

    def _check(self, step):
        """None when intact, else a human-readable defect description."""
        if self._is_v2(step):
            from . import async_checkpoint as ac
            return ac.check_sharded(self._v2_dir(step))
        man_path, pay_path = self._manifest(step), self._payload(step)
        try:
            with open(man_path, 'rb') as f:
                man = json.loads(f.read().decode())
        except (OSError, ValueError) as e:
            return 'unreadable manifest (%s)' % e
        if not os.path.isfile(pay_path):
            return 'payload missing'
        size = os.path.getsize(pay_path)
        if size != man.get('size'):
            return 'payload truncated/resized (%d bytes, manifest says %s)' \
                % (size, man.get('size'))
        crc = crc32_file(pay_path)
        if crc != man.get('crc32'):
            return 'payload CRC32 mismatch (0x%08x, manifest says 0x%08x)' \
                % (crc, man.get('crc32', 0))
        return None

    def _read_step(self, s, v1_numpy, return_extra):
        """(state, meta, extra) of an intact step, or a defect string."""
        from ..framework import _from_saveable
        defect = self._check(s)
        if defect is not None:
            return defect
        if self._is_v2(s):
            from . import async_checkpoint as ac
            try:
                state, meta, extra = ac.load_sharded(self._v2_dir(s),
                                                     return_extra=True)
            except Exception as e:    # CRC passed but deserialize failed
                return 'unreadable sharded payload (%s)' % e
            return state, meta, extra
        try:
            with open(self._payload(s), 'rb') as f:
                state = pickle.load(f)
        except Exception as e:   # CRC passed but unpickle failed
            return 'unpicklable payload (%s)' % e
        with open(self._manifest(s), 'rb') as f:
            meta = json.loads(f.read().decode()).get('meta', {})
        return _from_saveable(state, v1_numpy), meta, None

    def _load_any(self, step, v1_numpy, return_extra):
        """Newest intact checkpoint (or ``step``), with corrupt-skip
        fallback. Returns (state, meta, extra, step) or None."""
        candidates = [step] if step is not None else \
            list(reversed(self.steps()))
        sw = _obs.Stopwatch()
        for s in candidates:
            got = self._read_step(s, v1_numpy, return_extra)
            if not isinstance(got, str):
                state, meta, extra = got
                if _obs.enabled():
                    ms = sw.elapsed_ms()
                    _obs.histogram('checkpoint.restore_ms').observe(ms)
                    _obs.counter('checkpoint.restores').inc()
                    _obs.event('checkpoint.restore', step=s,
                               duration_ms=round(ms, 3))
                return state, meta, extra, s
            if _obs.enabled():
                _obs.counter('checkpoint.corrupt_skips').inc()
                _obs.event('checkpoint.corrupt', step=s, defect=str(got))
            warnings.warn(
                "CheckpointManager: checkpoint step %d at %r is corrupt "
                "(%s) — falling back to the previous good checkpoint"
                % (s, self.path, got))
        return None

    def load(self, step=None, return_numpy=False):
        """Return ``(state, meta)`` of checkpoint ``step`` (default: the
        newest NON-CORRUPT one), or ``None`` when nothing loadable exists.
        Corrupt checkpoints are skipped with a warning, never deleted —
        an operator may still salvage them. Sharded (format-2) checkpoints
        come back as plain numpy leaves."""
        got = self._load_any(step, return_numpy, return_extra=False)
        if got is None:
            return None
        state, meta, _extra, _s = got
        return state, meta

    def restore(self, step=None, sharding=None, return_extra=False):
        """``load()`` for training state, with resharding.

        Leaves come back as raw arrays (numpy for host restore). With
        ``sharding=`` (a ``ShardingConfig`` — or anything
        ``resolve_sharding`` accepts), an engine-layout state is placed
        straight onto the *target* mesh per its ``state_shardings`` — the
        checkpoint may have been saved on ANY mesh shape (k→k/2,
        sharded→replicated, ...); the reassembled bytes are identical, so
        the restore is bitwise-equal to a same-mesh restore. Returns
        ``(state, meta)`` (or ``(state, meta, extra)``), or None.
        """
        got = self._load_any(step, True, return_extra=return_extra)
        if got is None:
            return None
        state, meta, extra, _s = got
        if sharding is not None:
            from ..distributed.strategy import resolve_sharding
            from .async_checkpoint import place_with_config
            state = place_with_config(state, resolve_sharding(sharding))
        if return_extra:
            return state, meta, extra
        return state, meta

    def load_extra(self, step=None):
        """The pickled side payload (RNG streams, loop position) of a
        committed sharded checkpoint, WITHOUT reassembling the arrays;
        None when the step (default: newest) has no extra / is format 1."""
        import pickle as _pickle
        steps = [step] if step is not None else \
            list(reversed(self.steps()))
        for s in steps:
            if not self._is_v2(s):
                continue
            from . import async_checkpoint as ac
            try:
                man = ac.read_manifest(self._v2_dir(s))
                if not man.get('extra'):
                    return None
                with open(os.path.join(self._v2_dir(s),
                                       man['extra']['file']), 'rb') as f:
                    return _pickle.load(f)
            except Exception:
                return None
        return None

    def load_manifest(self, step):
        """The raw manifest dict of a committed step (either format)."""
        if self._is_v2(step):
            from . import async_checkpoint as ac
            return ac.read_manifest(self._v2_dir(step))
        with open(self._manifest(step), 'rb') as f:
            return json.loads(f.read().decode())


class _Crc32Writer:
    """File-like shim accumulating CRC32 + byte count as pickle streams."""

    def __init__(self, f):
        self._f = f
        self.crc = 0
        self.size = 0

    def write(self, data):
        self.crc = zlib.crc32(data, self.crc) & 0xFFFFFFFF
        self.size += len(data)
        return self._f.write(data)


# -- RNG capture for exact resume -------------------------------------------

def capture_rng():
    """Snapshot every RNG stream training consumes (paddle generator +
    global numpy), as plain pickleable python/numpy state."""
    import numpy as np
    from ..core import rng as _rng
    return {'paddle': _rng.get_rng_state(), 'numpy': np.random.get_state()}


def restore_rng(state):
    import numpy as np
    from ..core import rng as _rng
    if not state:
        return
    if state.get('paddle') is not None:
        _rng.set_rng_state(state['paddle'])
    if state.get('numpy') is not None:
        np.random.set_state(state['numpy'])
