"""CRC-stamped, rotating, fallback-capable checkpoint store.

Layout under a checkpoint directory::

    ckpt-00000012.ckpt            # pickled payload (Tensors -> numpy)
    ckpt-00000012.manifest.json   # {"format":1,"step":12,"size":...,"crc32":...,
                                  #  "meta":{"epoch":3,"step_in_epoch":0,...}}

Commit protocol: payload first, manifest second, both through
``atomic_io.atomic_write``. A checkpoint EXISTS only once its manifest does;
a crash between the two writes leaves an orphan payload that loaders ignore
and the next save of that step overwrites. ``load()`` walks steps newest
first, verifies size+CRC32 against the manifest, and transparently falls
back to the newest non-corrupt checkpoint (warning on every skip) — a torn
or bit-flipped latest file costs one checkpoint interval, not the run.
"""
import json
import os
import pickle
import warnings
import zlib

from .atomic_io import atomic_open, atomic_write, crc32_file
from .. import observability as _obs

__all__ = ['CheckpointManager', 'capture_rng', 'restore_rng']

_FMT = 1
_PREFIX = 'ckpt-'
_PAYLOAD_EXT = '.ckpt'
_MANIFEST_EXT = '.manifest.json'


class CheckpointManager:
    """Keep-last-N rotating checkpoint directory with corruption fallback."""

    def __init__(self, path, max_keep=3):
        self.path = os.fspath(path)
        self.max_keep = max_keep

    # -- naming -------------------------------------------------------------
    def _payload(self, step):
        return os.path.join(self.path, '%s%08d%s' % (_PREFIX, step,
                                                     _PAYLOAD_EXT))

    def _manifest(self, step):
        return os.path.join(self.path, '%s%08d%s' % (_PREFIX, step,
                                                     _MANIFEST_EXT))

    def steps(self):
        """Committed (manifest present) steps, ascending."""
        if not os.path.isdir(self.path):
            return []
        out = []
        for name in os.listdir(self.path):
            if name.startswith(_PREFIX) and name.endswith(_MANIFEST_EXT):
                digits = name[len(_PREFIX):-len(_MANIFEST_EXT)]
                if digits.isdigit():
                    out.append(int(digits))
        return sorted(out)

    def latest_step(self):
        s = self.steps()
        return s[-1] if s else None

    # -- write --------------------------------------------------------------
    def save(self, state, step=None, meta=None):
        """Atomically commit ``state`` (arbitrary pytree; Tensors become
        numpy payloads) as checkpoint ``step`` (default: latest+1)."""
        from ..framework import _to_saveable
        if step is None:
            latest = self.latest_step()
            step = 0 if latest is None else latest + 1
        step = int(step)
        pay_path = self._payload(step)
        sw = _obs.Stopwatch()
        with atomic_open(pay_path) as f:   # streamed: no full blob in RAM
            w = _Crc32Writer(f)
            pickle.dump(_to_saveable(state), w, protocol=4)
        # CRC/size accumulated while streaming — no read-back of a multi-GB
        # payload inside the preemption grace window
        manifest = {'format': _FMT, 'step': step, 'size': w.size,
                    'crc32': w.crc, 'meta': dict(meta or {})}
        atomic_write(self._manifest(step),
                     json.dumps(manifest, sort_keys=True).encode())
        self._rotate()
        if _obs.enabled():
            ms = sw.elapsed_ms()
            _obs.histogram('checkpoint.save_ms').observe(ms)
            _obs.counter('checkpoint.saves').inc()
            _obs.event('checkpoint.save', step=step, bytes=w.size,
                       duration_ms=round(ms, 3), meta=dict(meta or {}))
        return step

    def _rotate(self):
        if not self.max_keep:
            return
        for s in self.steps()[:-self.max_keep]:
            for p in (self._payload(s), self._manifest(s)):
                try:
                    os.unlink(p)
                except OSError:
                    pass

    # -- read ---------------------------------------------------------------
    def verify(self, step):
        """True iff checkpoint ``step``'s payload matches its manifest."""
        return self._check(step) is None

    def _check(self, step):
        """None when intact, else a human-readable defect description."""
        man_path, pay_path = self._manifest(step), self._payload(step)
        try:
            with open(man_path, 'rb') as f:
                man = json.loads(f.read().decode())
        except (OSError, ValueError) as e:
            return 'unreadable manifest (%s)' % e
        if not os.path.isfile(pay_path):
            return 'payload missing'
        size = os.path.getsize(pay_path)
        if size != man.get('size'):
            return 'payload truncated/resized (%d bytes, manifest says %s)' \
                % (size, man.get('size'))
        crc = crc32_file(pay_path)
        if crc != man.get('crc32'):
            return 'payload CRC32 mismatch (0x%08x, manifest says 0x%08x)' \
                % (crc, man.get('crc32', 0))
        return None

    def load(self, step=None, return_numpy=False):
        """Return ``(state, meta)`` of checkpoint ``step`` (default: the
        newest NON-CORRUPT one), or ``None`` when nothing loadable exists.
        Corrupt checkpoints are skipped with a warning, never deleted —
        an operator may still salvage them."""
        from ..framework import _from_saveable
        candidates = [step] if step is not None else \
            list(reversed(self.steps()))
        sw = _obs.Stopwatch()
        for s in candidates:
            defect = self._check(s)
            if defect is None:
                try:
                    with open(self._payload(s), 'rb') as f:
                        state = pickle.load(f)
                except Exception as e:   # CRC passed but unpickle failed
                    defect = 'unpicklable payload (%s)' % e
                else:
                    with open(self._manifest(s), 'rb') as f:
                        meta = json.loads(f.read().decode()).get('meta', {})
                    if _obs.enabled():
                        ms = sw.elapsed_ms()
                        _obs.histogram('checkpoint.restore_ms').observe(ms)
                        _obs.counter('checkpoint.restores').inc()
                        _obs.event('checkpoint.restore', step=s,
                                   duration_ms=round(ms, 3))
                    return _from_saveable(state, return_numpy), meta
            if _obs.enabled():
                _obs.counter('checkpoint.corrupt_skips').inc()
                _obs.event('checkpoint.corrupt', step=s, defect=str(defect))
            warnings.warn(
                "CheckpointManager: checkpoint step %d at %r is corrupt "
                "(%s) — falling back to the previous good checkpoint"
                % (s, self.path, defect))
        return None


class _Crc32Writer:
    """File-like shim accumulating CRC32 + byte count as pickle streams."""

    def __init__(self, f):
        self._f = f
        self.crc = 0
        self.size = 0

    def write(self, data):
        self.crc = zlib.crc32(data, self.crc) & 0xFFFFFFFF
        self.size += len(data)
        return self._f.write(data)


# -- RNG capture for exact resume -------------------------------------------

def capture_rng():
    """Snapshot every RNG stream training consumes (paddle generator +
    global numpy), as plain pickleable python/numpy state."""
    import numpy as np
    from ..core import rng as _rng
    return {'paddle': _rng.get_rng_state(), 'numpy': np.random.get_state()}


def restore_rng(state):
    import numpy as np
    from ..core import rng as _rng
    if not state:
        return
    if state.get('paddle') is not None:
        _rng.set_rng_state(state['paddle'])
    if state.get('numpy') is not None:
        np.random.set_state(state['numpy'])
