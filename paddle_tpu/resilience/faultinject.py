"""Deterministic fault injection: make every resilience behavior testable.

The harness produces exactly the failures the resilience layer defends
against, on CPU, deterministically:

- ``fail_writes`` — the next N atomic writes raise before commit (torn-write
  crash model; destinations must stay intact);
- ``corrupt_file``/``truncate_file`` — flip or drop committed bytes (disk
  corruption model; manifests must catch it);
- ``flaky`` — wrap a callable to fail its first N calls (transient-network
  model for retry());
- ``poison_loss`` — wrap a loss fn to return NaN at chosen global steps
  (NaN-guard model);
- ``PreemptAtStep`` — a hapi callback that delivers a real SIGTERM to this
  process at a chosen global batch (preemption model);
- ``poison_sample`` / ``kill_worker`` / ``hang_worker`` — Dataset wrappers
  producing a raising sample, a worker-process SIGKILL, or a worker hang at
  chosen indices (DataLoader quarantine / respawn / watchdog models);
- ``slow_rank`` — a picklable spawn-func wrapper adding a delay on one rank
  (straggler model for collective deadlines);
- ``slow_model`` — wrap a serving batch callable to sleep before every
  batch (overloaded-backend model for deadline expiry / load shedding);
- ``latency_ramp`` — wrap a callable so each call sleeps a little longer
  than the last (slow-degradation model: no call is an outlier, only the
  trend is wrong — drives the doctor's ``latency_creep`` detector);
- ``slow_loader`` — Dataset wrapper sleeping before EVERY sample (the
  input-bound model the anomaly doctor's dataloader-wait detector names);
- ``retrace_bait`` — run n jitted calls with n distinct static shapes,
  deterministically inflating the ``jax.compiles`` counter (retrace-storm
  model for the anomaly doctor / GL005-GL006-adjacent telemetry);
- ``slow_collective`` — context manager delaying named eager collectives in
  this process (DistributedTimeoutError model);
- ``boot_fail`` — context manager arming rank bootstrap crashes (exit 43
  before the started marker) for supervised-launch restart tests;
- ``kill_replica_at_request`` / ``hang_replica`` / ``slow_replica`` —
  serving-replica chaos (siblings of ``kill_rank_at_step``/``slow_rank``):
  abrupt engine death right after admitting the Nth request, a wedged
  scheduler that stays "alive" while nothing progresses, and a per-pump
  delay producing a deterministic p99 straggler for hedging tests;
- ``tenant_storm`` — deterministic Poisson request bursts from one tenant
  through ``submit()`` (engine, endpoint, or router) over VIRTUAL ticks —
  the noisy-neighbor model for per-tenant quotas, weighted-fair admission,
  and the doctor's ``noisy_neighbor`` detector; no wall-clock sleeps;
- ``burn_ramp`` — fabricate completed-request judgments straight into the
  SLO tracker so a model's error-budget burn rate reaches a chosen level
  deterministically (the sustained-burn model the fleet autoscaler's grow
  path and the doctor's ``slo_burn`` detector key on) without real traffic;
- ``hold_lock`` / ``RacingCall`` — the forced-interleaving hooks for data-
  race regression tests (graftlint GC001-class bugs): freeze a writer at
  its guarded critical section by holding the guard from the test thread,
  launch the racing call on a side thread with completion observability,
  assert it blocks, release, assert it lands. Deterministic: the schedule
  is pinned by the lock itself, not by sleeps.

All injectors are context-managed or idempotent to deactivate, so a failing
test cannot leak faults into the next one.
"""
import contextlib
import os
import signal
import time

from . import atomic_io

__all__ = ['FaultInjector', 'flaky', 'poison_loss', 'corrupt_file',
           'corrupt_compile_cache',
           'truncate_file', 'PreemptAtStep', 'InjectedWriteError',
           'poison_sample', 'kill_worker', 'hang_worker', 'slow_rank',
           'slow_model', 'latency_ramp', 'slow_loader', 'slow_collective',
           'retrace_bait',
           'boot_fail', 'PoisonedSampleError', 'slow_fs', 'disk_full',
           'sigterm_at_step', 'kill_rank_at_step', 'kill_replica_at_request',
           'hang_replica', 'slow_replica', 'ReplicaHang', 'hold_lock',
           'RacingCall', 'tenant_storm', 'burn_ramp']


class InjectedWriteError(OSError):
    """The injected failure for write faults."""


class FaultInjector:
    """Context manager arming write faults against atomic_io.

    >>> with FaultInjector().fail_writes(times=1, match='model'):
    ...     paddle.save(state, 'model.pdparams')   # raises, file untouched
    """

    def __init__(self):
        self._arms = []       # list of [stage, remaining, match]
        self._stream_arms = []   # list of [kind, param, match, remaining]
        self._prev_hook = None
        self._prev_stream = None
        self.triggered = 0

    def fail_writes(self, times=1, match=None, stage='write'):
        """Arm: the next ``times`` atomic writes whose destination contains
        ``match`` (substring; None = all) raise ``InjectedWriteError`` at
        ``stage`` ('write' = before any bytes, 'replace' = staged bytes
        written but commit rename never happens)."""
        self._arms.append([stage, times, match])
        return self

    def disk_full(self, after_bytes=0, match=None, times=1):
        """Arm: the next ``times`` atomic writes whose destination contains
        ``match`` hit ENOSPC once ``after_bytes`` staged bytes are down —
        the disk-fills-mid-shard model. The commit never happens, the temp
        is removed, and the destination (and every previously committed
        checkpoint) stays intact."""
        self._stream_arms.append(['enospc', int(after_bytes), match,
                                  int(times)])
        return self

    def slow_fs(self, delay_s, match=None):
        """Arm: every staged ``write()`` to a matching destination sleeps
        ``delay_s`` first — the NFS-on-a-bad-day model that makes a
        synchronous checkpoint save stall the training thread visibly (and
        an async one provably not)."""
        self._stream_arms.append(['slow', float(delay_s), match, None])
        return self

    def _hook(self, stage, path):
        for arm in self._arms:
            a_stage, remaining, match = arm
            if a_stage != stage or remaining <= 0:
                continue
            if match is not None and match not in os.fspath(path):
                continue
            arm[1] -= 1
            self.triggered += 1
            raise InjectedWriteError(
                "fault injection: forced %s failure for %r" % (stage, path))

    def _stream(self, path, so_far, chunk_len):
        for arm in self._stream_arms:
            kind, param, match, remaining = arm
            if match is not None and match not in os.fspath(path):
                continue
            if kind == 'slow':
                time.sleep(param)
            elif kind == 'enospc':
                if remaining <= 0 or so_far + chunk_len <= param:
                    continue
                arm[3] -= 1
                self.triggered += 1
                import errno
                raise OSError(
                    errno.ENOSPC,
                    "fault injection: no space left on device after "
                    "%d bytes of %r" % (so_far, path))

    def __enter__(self):
        # both hooks install unconditionally: arming disk_full/slow_fs
        # AFTER entering (like fail_writes allows) must work, not silently
        # inject nothing
        self._prev_hook = atomic_io._fault_hook
        atomic_io._fault_hook = self._hook
        self._prev_stream = atomic_io._stream_hook
        atomic_io._stream_hook = self._stream
        return self

    def __exit__(self, *exc):
        atomic_io._fault_hook = self._prev_hook
        atomic_io._stream_hook = self._prev_stream
        return False


def flaky(fn, fail_times=1, exc_factory=None):
    """Wrap ``fn`` to raise on its first ``fail_times`` calls, succeed after.
    The wrapper exposes ``.calls`` (total) and ``.failures`` (raised)."""
    state = {'calls': 0}

    def wrapper(*args, **kwargs):
        state['calls'] += 1
        if state['calls'] <= fail_times:
            if exc_factory is not None:
                raise exc_factory(state['calls'])
            raise ConnectionError(
                "fault injection: flaky call %d/%d failing"
                % (state['calls'], fail_times))
        return fn(*args, **kwargs)

    wrapper.state = state
    return wrapper


def poison_loss(loss_fn, at_steps):
    """Wrap a loss callable: at the given 0-based global call indices the
    returned loss is multiplied by NaN (keeps shape/dtype/graph so the guard
    sees exactly what a numeric blow-up produces)."""
    at_steps = set(int(s) for s in at_steps)
    state = {'calls': 0}

    def wrapper(*args, **kwargs):
        step = state['calls']
        state['calls'] += 1
        loss = loss_fn(*args, **kwargs)
        if step in at_steps:
            return loss * float('nan')
        return loss

    wrapper.state = state
    return wrapper


def corrupt_file(path, offset=0, nbytes=1):
    """Flip ``nbytes`` bytes of a committed file in place at ``offset``
    (negative offset = from end)."""
    size = os.path.getsize(path)
    if offset < 0:
        offset = max(0, size + offset)
    with open(path, 'r+b') as f:
        f.seek(offset)
        block = f.read(nbytes)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in block))
    return path


def corrupt_compile_cache(cache_dir, n=None, mode='corrupt'):
    """Damage committed persistent-compile-cache entries (deterministic
    repro for the doctor's ``cold_compile_storm`` detector and the
    compilecache incompat-fallback tests).

    ``mode='corrupt'`` XOR-flips a byte mid-payload in the first ``n``
    entry files (all when ``n`` is None) — the CRC manifest catches it at
    load. ``mode='truncate'`` tears them instead. ``mode='skew'`` rewrites
    the manifest's recorded jax version to a fake one — the version gate
    rejects every entry with untouched bytes. Returns the list of damaged
    paths (or the manifest path for ``skew``)."""
    import json
    manifest = os.path.join(cache_dir, 'manifest.json')
    with open(manifest, 'rb') as f:
        doc = json.loads(f.read().decode('utf-8'))
    entries = doc.get('entries', {})
    if mode == 'skew':
        for ent in entries.values():
            ent['jax'] = '0.0.faultinjected'
        with open(manifest, 'w', encoding='utf-8') as f:
            json.dump(doc, f)
        return [manifest]
    damaged = []
    for key in sorted(entries):
        if n is not None and len(damaged) >= int(n):
            break
        path = os.path.join(cache_dir, entries[key].get('file', ''))
        if not os.path.exists(path):
            continue
        if mode == 'truncate':
            truncate_file(path)
        else:
            # mid-payload: headers tearing too would fail unpickle before
            # the CRC check — the CRC must be what catches it
            corrupt_file(path, offset=os.path.getsize(path) // 2)
        damaged.append(path)
    return damaged


def truncate_file(path, keep_bytes=None, drop_bytes=None):
    """Truncate a committed file to ``keep_bytes`` (or drop ``drop_bytes``
    from the end) — the classic torn-write artifact."""
    size = os.path.getsize(path)
    if keep_bytes is None:
        keep_bytes = max(0, size - (drop_bytes if drop_bytes is not None
                                    else size // 2))
    with open(path, 'r+b') as f:
        f.truncate(keep_bytes)
    return path


class PoisonedSampleError(ValueError):
    """The injected failure for poisoned dataset samples."""


class _DatasetWrapper:
    """Picklable (top-level class) Dataset wrapper base: forwards len() and
    __getitem__, letting subclasses inject at chosen indices. Fork-safe —
    state is plain attributes copied into each worker."""

    def __init__(self, dataset, at_indices):
        self._dataset = dataset
        self._at = set(int(i) for i in at_indices)

    def __len__(self):
        return len(self._dataset)

    def __getitem__(self, i):
        if int(i) in self._at:
            self._inject(i)
        return self._dataset[i]

    def _inject(self, i):
        raise NotImplementedError


class _PoisonedDataset(_DatasetWrapper):
    def _inject(self, i):
        raise PoisonedSampleError(
            f"fault injection: poisoned sample at index {i}")


def poison_sample(dataset, at_indices):
    """Dataset wrapper raising ``PoisonedSampleError`` for the given
    indices — the corrupt-record model the DataLoader quarantine defends
    against."""
    return _PoisonedDataset(dataset, at_indices)


class _KillerDataset(_DatasetWrapper):
    """SIGKILL the current process when a chosen index is fetched — but
    only in a process that is NOT the one that built the wrapper, so a
    threaded DataLoader (or the parent's shm-probe fetch) can never shoot
    the trainer itself. ``once_file`` (required) makes the kill one-shot
    across respawns: the first victim leaves a marker, the respawned
    worker survives the same index."""

    def __init__(self, dataset, at_indices, once_file):
        super().__init__(dataset, at_indices)
        self._builder_pid = os.getpid()
        self._once_file = os.fspath(once_file)

    def _inject(self, i):
        if os.getpid() == self._builder_pid:
            return   # parent/threaded fetch: never kill the trainer
        if os.path.exists(self._once_file):
            return   # already fired once; the respawned worker survives
        with open(self._once_file, 'w'):
            pass
        os.kill(os.getpid(), signal.SIGKILL)


def kill_worker(dataset, at_index, once_file):
    """Dataset wrapper that SIGKILLs the (process) worker fetching
    ``at_index``, once — the crashed-worker model for respawn tests."""
    return _KillerDataset(dataset, [at_index], once_file)


class _HangingDataset(_DatasetWrapper):
    def __init__(self, dataset, at_indices, hang_s):
        super().__init__(dataset, at_indices)
        self._hang_s = float(hang_s)

    def _inject(self, i):
        time.sleep(self._hang_s)


def hang_worker(dataset, at_index, hang_s=5.0):
    """Dataset wrapper that sleeps ``hang_s`` seconds fetching
    ``at_index`` — the wedged-worker model for the deadlock watchdog."""
    return _HangingDataset(dataset, [at_index], hang_s)


class _SlowRankFn:
    """Picklable spawn-func wrapper: rank ``rank`` sleeps ``delay_s``
    before running — the straggler model for collective deadlines and
    join(timeout) supervision."""

    def __init__(self, fn, rank, delay_s):
        self.fn = fn
        self.rank = int(rank)
        self.delay_s = float(delay_s)

    def __call__(self, *args, **kwargs):
        if int(os.environ.get('PADDLE_TRAINER_ID', '0')) == self.rank:
            time.sleep(self.delay_s)
        return self.fn(*args, **kwargs)


def slow_rank(fn, rank, delay_s):
    return _SlowRankFn(fn, rank, delay_s)


def slow_model(fn, delay_s):
    """Wrap a serving batch callable so every batch sleeps ``delay_s``
    seconds first — the overloaded-backend model that drives serving
    deadline expiry and admission-queue load shedding deterministically
    on CPU (the serving-side sibling of ``slow_rank``)."""
    delay_s = float(delay_s)

    def slowed(*args, **kwargs):
        time.sleep(delay_s)
        return fn(*args, **kwargs)
    return slowed


def latency_ramp(fn, per_call_ms, start_ms=0.0):
    """Wrap a callable so call ``k`` sleeps ``start_ms + k*per_call_ms``
    milliseconds first — each call a little slower than the last. The
    slow-degradation model (resource exhaustion, fragmentation, thermal
    creep) behind the doctor's ``latency_creep`` detector: no single call
    is an outlier, only the TREND is wrong, which is exactly what a
    point-in-time snapshot cannot see. Deterministic: the ramp depends
    only on the call count. ``slowed.calls`` exposes it."""
    per_call_s = float(per_call_ms) / 1e3
    start_s = float(start_ms) / 1e3

    def slowed(*args, **kwargs):
        time.sleep(start_s + slowed.calls * per_call_s)
        slowed.calls += 1
        return fn(*args, **kwargs)
    slowed.calls = 0
    return slowed


class _SlowDataset(_DatasetWrapper):
    def __init__(self, dataset, delay_s):
        super().__init__(dataset, range(len(dataset)))
        self._delay_s = float(delay_s)

    def _inject(self, i):
        time.sleep(self._delay_s)


def slow_loader(dataset, delay_s):
    """Dataset wrapper sleeping ``delay_s`` seconds before EVERY sample —
    the input-bound model: the dataloader wait histogram dominates step
    time and the anomaly doctor names the run ``input_bound``."""
    return _SlowDataset(dataset, delay_s)


def retrace_bait(n=8, base=4):
    """Deterministically trigger ``n`` fresh XLA compiles by jitting one
    trivial function over ``n`` DISTINCT static shapes — the retrace-storm
    signature (a shape or hash key changing every call) without needing a
    buggy model. Returns the number of baited calls. Telemetry's
    ``jax.compiles`` counter absorbs them when enabled."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _poke(x):
        return x + 1

    for i in range(int(n)):
        jax.block_until_ready(_poke(jnp.zeros((int(base) + i,),
                                              jnp.float32)))
    return int(n)


@contextlib.contextmanager
def slow_collective(delay_s, ops=None):
    """Delay every eager collective launch in this process by ``delay_s``
    seconds (optionally only the named ``ops``) — deterministically drives
    ``distributed.set_timeout`` deadlines to expiry on CPU."""
    from ..distributed import deadline as _deadline
    only = set(ops) if ops else None

    def hook(op):
        if only is None or op in only:
            time.sleep(delay_s)

    prev = _deadline._delay_hook[0]
    _deadline._delay_hook[0] = hook
    try:
        yield
    finally:
        _deadline._delay_hook[0] = prev


@contextlib.contextmanager
def slow_fs(delay_s, match=None):
    """Context manager: every staged atomic write in this process sleeps
    ``delay_s`` per ``write()`` call (optionally only destinations
    containing ``match``) — the slow-filesystem model behind the
    async-checkpoint save-stall comparison and the preemption fence
    regression test."""
    with FaultInjector().slow_fs(delay_s, match=match):
        yield


@contextlib.contextmanager
def disk_full(after_bytes=0, match=None, times=1):
    """Context manager: ENOSPC partway through the next ``times`` staged
    writes (see :meth:`FaultInjector.disk_full`)."""
    with FaultInjector().disk_full(after_bytes=after_bytes, match=match,
                                   times=times) as fi:
        yield fi


def sigterm_at_step(data, at_step):
    """Wrap a batch iterable: a real SIGTERM is raised in this process
    just before item ``at_step`` (0-based, counted across the wrapper's
    lifetime) is yielded — the preemption model for ``engine.fit`` loops
    (the hapi sibling is :class:`PreemptAtStep`). The item itself is still
    yielded, so the loop's PreemptionGuard sees the flag at the *next*
    step boundary, exactly like a scheduler-delivered signal."""
    return _SigtermIter(data, at_step)


class _SigtermIter:
    """Iterator behind :func:`sigterm_at_step`; exposes ``.state``
    (``seen``/``fired``) so a test can assert the signal really fired."""

    def __init__(self, data, at_step):
        self._it = iter(data)
        self._at = int(at_step)
        self.state = {'seen': 0, 'fired': False}

    def __iter__(self):
        return self

    def __next__(self):
        item = next(self._it)
        if self.state['seen'] == self._at and not self.state['fired']:
            self.state['fired'] = True
            signal.raise_signal(signal.SIGTERM)
        self.state['seen'] += 1
        return item


def kill_rank_at_step(at_step, once_file, rank=None):
    """The rank-death model for elastic training: returns ``maybe_die(step)``
    — call it once per training step; at global step ``at_step`` it SIGKILLs
    the CURRENT process (optionally only when ``PADDLE_TRAINER_ID == rank``),
    once across restarts (``once_file`` marker: the relaunched generation
    survives the same step)."""
    at_step = int(at_step)
    once_file = os.fspath(once_file)

    def maybe_die(step):
        if int(step) != at_step:
            return
        if rank is not None and \
                int(os.environ.get('PADDLE_TRAINER_ID', '0')) != int(rank):
            return
        if os.path.exists(once_file):
            return   # already fired once: the respawned rank survives
        with open(once_file, 'w'):   # atomic-ok: chaos one-shot marker
            pass
        os.kill(os.getpid(), signal.SIGKILL)

    return maybe_die


def kill_replica_at_request(engine, at_request):
    """Serving sibling of ``kill_rank_at_step``: arm ``engine`` to die
    abruptly (``ServingEngine.kill()``) immediately after ADMITTING its
    ``at_request``-th request (1-indexed, counted across models; shed
    submissions don't count). The just-admitted request and everything
    already queued/resident is stranded exactly as a real crash strands
    it — recovering the loss is the router's job, which is the point.
    Returns ``engine``; no unwrap needed — a dead engine stays dead."""
    at_request = int(at_request)
    if at_request < 1:
        raise ValueError("kill_replica_at_request: at_request is 1-indexed")
    state = {'admitted': 0}
    orig = engine.submit

    def submit(model, inputs, **kw):
        pending = orig(model, inputs, **kw)
        state['admitted'] += 1
        if state['admitted'] == at_request:
            engine.kill()
        return pending

    engine.submit = submit
    return engine


class ReplicaHang:
    """Handle from :func:`hang_replica` — ``release()`` un-wedges the
    replica (restores the original pump)."""

    def __init__(self, engine, orig_pump):
        self._engine = engine
        self._orig = orig_pump
        self.released = False

    def release(self):
        self._engine.pump = self._orig
        self.released = True


def hang_replica(engine):
    """Wedge ``engine``: every pump does NOTHING (the worker thread stays
    alive, liveness checks pass, queues grow, no request progresses)
    until the returned handle's ``release()`` — the hung-replica model (a
    deadlocked device, a stuck host callback) that is invisible to
    ``dispatchable()`` and only a router's attempt timeout or hedge can
    route around. Returns a :class:`ReplicaHang`."""
    orig = engine.pump
    hang = ReplicaHang(engine, orig)

    def pump():
        if hang.released:
            return orig()
        # bounded no-op tick: the worker must stay responsive to stop()
        time.sleep(0.005)
        return False

    engine.pump = pump
    return hang


def slow_replica(engine, delay_s):
    """Every scheduler pump on ``engine`` sleeps ``delay_s`` first — the
    degraded-replica model (overheating host, noisy neighbor) whose tail
    latency makes hedged-request wins deterministic on CPU (the replica
    sibling of ``slow_model``/``slow_rank``). Returns ``engine``; assign
    ``engine.pump`` back (or just stop the engine) to deactivate."""
    delay_s = float(delay_s)
    orig = engine.pump

    def pump():
        time.sleep(delay_s)
        return orig()

    engine.pump = pump
    return engine


@contextlib.contextmanager
def boot_fail(rank, times=1):
    """Arm ``times`` bootstrap crashes (os._exit(43) before the started
    marker) for ``rank`` in every supervised spawn/launch child started
    inside the context — the transient-bringup model bounded restart
    (max_restarts) exists for."""
    key = 'PADDLE_TPU_FI_BOOT_FAIL'
    prev = os.environ.get(key)
    os.environ[key] = f"{int(rank)}:{int(times)}"
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = prev


class PreemptAtStep:
    """hapi callback delivering a real SIGTERM at the end of global batch
    ``step`` (0-based, counted across epochs) — exercises the full
    PreemptionGuard -> CheckpointSaver -> stop_training path.

    Imported lazily as a Callback subclass so this module stays stdlib-only
    until a test actually uses it.
    """

    def __new__(cls, step):
        from ..hapi.callbacks import Callback

        class _Preempter(Callback):
            def __init__(self, at):
                super().__init__()
                self.at = int(at)
                self.seen = 0
                self.fired = False

            def on_train_batch_end(self, batch_step, logs=None):
                if self.seen == self.at and not self.fired:
                    self.fired = True
                    signal.raise_signal(signal.SIGTERM)
                self.seen += 1

        return _Preempter(step)


def _poisson(rng, lam):
    """Knuth's Poisson sampler off a seeded ``random.Random`` — the burst
    sizes are a pure function of (seed, draw index)."""
    import math
    limit = math.exp(-float(lam))
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1


def tenant_storm(target, model, inputs, tenant='storm', qps=8.0,
                 duration_ticks=10, seed=0, **submit_kw):
    """Deterministic noisy-neighbor traffic: Poisson bursts from one tenant.

    Each of ``duration_ticks`` VIRTUAL ticks draws a Poisson(``qps``)
    burst size from a seeded RNG and fires that many ``target.submit(model,
    inputs, tenant=tenant)`` calls back-to-back — ``target`` is anything
    with the serving submit signature (``ServingEngine``, ``Endpoint.submit``
    host object, ``FleetRouter``). No wall-clock sleeps anywhere: "qps" is
    per virtual tick, so the same seed always produces the same burst
    train and the same shed pattern, and the caller pumps/settles between
    ticks however its harness drives the engine.

    Over-quota and over-capacity submits (``QueueFullError`` and
    subclasses, e.g. ``QuotaExceededError``) are absorbed and tallied by
    their ``reason``. Returns::

        {'attempts': int, 'submitted': int, 'shed': {reason: n},
         'per_tick': [burst sizes], 'pending': [admitted handles]}

    so a test can assert the storm really was shed as ``quota`` (not
    ``queue_full``) and still settle the admitted remainder.
    """
    import random
    from ..serving.scheduler import QueueFullError
    rng = random.Random(int(seed))
    out = {'attempts': 0, 'submitted': 0, 'shed': {},
           'per_tick': [], 'pending': []}
    for _ in range(int(duration_ticks)):
        burst = _poisson(rng, qps)
        out['per_tick'].append(burst)
        for _ in range(burst):
            out['attempts'] += 1
            try:
                pending = target.submit(model, inputs, tenant=tenant,
                                        **submit_kw)
            except QueueFullError as e:
                reason = getattr(e, 'reason', 'queue_full')
                out['shed'][reason] = out['shed'].get(reason, 0) + 1
            else:
                out['submitted'] += 1
                out['pending'].append(pending)
    return out


def burn_ramp(model, burn=2.0, requests=20, target_ms=50.0,
              objective=0.9):
    """Drive ``model``'s SLO error-budget burn rate to ``burn``, now.

    Feeds ``requests`` fabricated completed-request judgments straight
    into the SLO tracker (``observability.slo.record``): the fraction
    needed for the target burn is recorded as over-target latencies
    (status ``'ok'`` but 2x the objective — exactly what a degrading
    backend produces), the rest comfortably under it. Registers a
    ``target_ms``/``objective`` objective when the model has none. Burn
    is a cumulative ratio, so one call *sustains*: every subsequent
    autoscaler/doctor observation sees the same rate until real traffic
    or ``slo.reset()`` dilutes it — which is what makes "sustained burn
    for N ticks" testable without wall-clock time. Returns the achieved
    burn rate.
    """
    from ..observability import slo as _slo
    obj = _slo.objective(model)
    if obj is None:
        obj = _slo.set_objective(model, target_ms, objective)
    budget = max(1.0 - obj['objective'], 1e-9)
    requests = max(1, int(requests))
    # burn = (violations/requests)/budget  =>  violations to fabricate:
    violations = min(requests, max(0, round(float(burn) * budget
                                            * requests)))
    achieved = None
    for i in range(requests):
        if i < violations:
            achieved = _slo.record(model, 'ok', obj['target_ms'] * 2.0)
        else:
            achieved = _slo.record(model, 'ok', obj['target_ms'] * 0.5)
    return achieved


@contextlib.contextmanager
def hold_lock(lock):
    """Freeze every writer that must take ``lock`` — the deterministic
    interleaving hook for data-race regression tests. Acquire the guard on
    the test thread, launch the racing call (``RacingCall``), assert it has
    NOT completed (it is parked at the exact formerly-racy critical
    section), release, assert it lands. A reverted fix turns the "still
    blocked" assertion false immediately — no timing luck involved."""
    lock.acquire()
    try:
        yield lock
    finally:
        lock.release()


class RacingCall:
    """A call launched on a daemon side thread with completion
    observability — the other half of ``hold_lock``.

    ``done`` is set when the call finished (result or exception);
    ``blocked(grace)`` waits ``grace`` seconds and reports True while the
    call is still parked; ``join()`` waits (watchdog-bounded) and returns
    the result, re-raising any error from the side thread."""

    def __init__(self, fn, *args, **kwargs):
        import threading
        self.done = threading.Event()
        self.result = None
        self.error = None

        def _run():
            try:
                self.result = fn(*args, **kwargs)
            except BaseException as e:   # re-raised in join()
                self.error = e
            finally:
                self.done.set()

        self._thread = threading.Thread(
            target=_run, name='paddle-tpu-racing-call', daemon=True)
        self._thread.start()

    def blocked(self, grace=0.15):
        """True when the call is still parked after ``grace`` seconds."""
        return not self.done.wait(grace)

    def join(self, timeout=5.0):
        from .watchdog import join_thread
        join_thread(self._thread, timeout=timeout)
        if self.error is not None:
            raise self.error
        return self.result
