"""Deterministic fault injection: make every resilience behavior testable.

The harness produces exactly the failures the resilience layer defends
against, on CPU, deterministically:

- ``fail_writes`` — the next N atomic writes raise before commit (torn-write
  crash model; destinations must stay intact);
- ``corrupt_file``/``truncate_file`` — flip or drop committed bytes (disk
  corruption model; manifests must catch it);
- ``flaky`` — wrap a callable to fail its first N calls (transient-network
  model for retry());
- ``poison_loss`` — wrap a loss fn to return NaN at chosen global steps
  (NaN-guard model);
- ``PreemptAtStep`` — a hapi callback that delivers a real SIGTERM to this
  process at a chosen global batch (preemption model).

All injectors are context-managed or idempotent to deactivate, so a failing
test cannot leak faults into the next one.
"""
import os
import signal

from . import atomic_io

__all__ = ['FaultInjector', 'flaky', 'poison_loss', 'corrupt_file',
           'truncate_file', 'PreemptAtStep', 'InjectedWriteError']


class InjectedWriteError(OSError):
    """The injected failure for write faults."""


class FaultInjector:
    """Context manager arming write faults against atomic_io.

    >>> with FaultInjector().fail_writes(times=1, match='model'):
    ...     paddle.save(state, 'model.pdparams')   # raises, file untouched
    """

    def __init__(self):
        self._arms = []       # list of [stage, remaining, match]
        self._prev_hook = None
        self.triggered = 0

    def fail_writes(self, times=1, match=None, stage='write'):
        """Arm: the next ``times`` atomic writes whose destination contains
        ``match`` (substring; None = all) raise ``InjectedWriteError`` at
        ``stage`` ('write' = before any bytes, 'replace' = staged bytes
        written but commit rename never happens)."""
        self._arms.append([stage, times, match])
        return self

    def _hook(self, stage, path):
        for arm in self._arms:
            a_stage, remaining, match = arm
            if a_stage != stage or remaining <= 0:
                continue
            if match is not None and match not in os.fspath(path):
                continue
            arm[1] -= 1
            self.triggered += 1
            raise InjectedWriteError(
                "fault injection: forced %s failure for %r" % (stage, path))

    def __enter__(self):
        self._prev_hook = atomic_io._fault_hook
        atomic_io._fault_hook = self._hook
        return self

    def __exit__(self, *exc):
        atomic_io._fault_hook = self._prev_hook
        return False


def flaky(fn, fail_times=1, exc_factory=None):
    """Wrap ``fn`` to raise on its first ``fail_times`` calls, succeed after.
    The wrapper exposes ``.calls`` (total) and ``.failures`` (raised)."""
    state = {'calls': 0}

    def wrapper(*args, **kwargs):
        state['calls'] += 1
        if state['calls'] <= fail_times:
            if exc_factory is not None:
                raise exc_factory(state['calls'])
            raise ConnectionError(
                "fault injection: flaky call %d/%d failing"
                % (state['calls'], fail_times))
        return fn(*args, **kwargs)

    wrapper.state = state
    return wrapper


def poison_loss(loss_fn, at_steps):
    """Wrap a loss callable: at the given 0-based global call indices the
    returned loss is multiplied by NaN (keeps shape/dtype/graph so the guard
    sees exactly what a numeric blow-up produces)."""
    at_steps = set(int(s) for s in at_steps)
    state = {'calls': 0}

    def wrapper(*args, **kwargs):
        step = state['calls']
        state['calls'] += 1
        loss = loss_fn(*args, **kwargs)
        if step in at_steps:
            return loss * float('nan')
        return loss

    wrapper.state = state
    return wrapper


def corrupt_file(path, offset=0, nbytes=1):
    """Flip ``nbytes`` bytes of a committed file in place at ``offset``
    (negative offset = from end)."""
    size = os.path.getsize(path)
    if offset < 0:
        offset = max(0, size + offset)
    with open(path, 'r+b') as f:
        f.seek(offset)
        block = f.read(nbytes)
        f.seek(offset)
        f.write(bytes(b ^ 0xFF for b in block))
    return path


def truncate_file(path, keep_bytes=None, drop_bytes=None):
    """Truncate a committed file to ``keep_bytes`` (or drop ``drop_bytes``
    from the end) — the classic torn-write artifact."""
    size = os.path.getsize(path)
    if keep_bytes is None:
        keep_bytes = max(0, size - (drop_bytes if drop_bytes is not None
                                    else size // 2))
    with open(path, 'r+b') as f:
        f.truncate(keep_bytes)
    return path


class PreemptAtStep:
    """hapi callback delivering a real SIGTERM at the end of global batch
    ``step`` (0-based, counted across epochs) — exercises the full
    PreemptionGuard -> CheckpointSaver -> stop_training path.

    Imported lazily as a Callback subclass so this module stays stdlib-only
    until a test actually uses it.
    """

    def __new__(cls, step):
        from ..hapi.callbacks import Callback

        class _Preempter(Callback):
            def __init__(self, at):
                super().__init__()
                self.at = int(at)
                self.seen = 0
                self.fired = False

            def on_train_batch_end(self, batch_step, logs=None):
                if self.seen == self.at and not self.fired:
                    self.fired = True
                    signal.raise_signal(signal.SIGTERM)
                self.seen += 1

        return _Preempter(step)
