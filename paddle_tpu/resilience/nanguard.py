"""NaN/Inf step guard: skip poisoned updates instead of corrupting the run.

A single non-finite loss on a long TPU run poisons every parameter the moment
the update applies; the guard checks the loss before backward (eager) or the
pre-update snapshot (jit) and skips the step. It cooperates with the dynamic
``amp.GradScaler``: a skipped step is reported as a found-inf event so the
loss scale backs off through the scaler's existing decrement path — the two
mechanisms see a consistent count of bad steps.
"""
import numpy as np

from .. import observability as _obs

__all__ = ['NanGuard', 'NanStepError']


class NanStepError(RuntimeError):
    """Raised when ``max_consecutive_skips`` poisoned steps occur in a row —
    at that point the run is diverging, not hitting a transient spike."""


class NanGuard:
    def __init__(self, max_consecutive_skips=25, scaler=None, verbose=True):
        self.max_consecutive_skips = max_consecutive_skips
        self.skipped_steps = 0
        self.consecutive_skips = 0
        self.total_steps = 0
        self._scaler = scaler
        self._verbose = verbose

    def attach_scaler(self, scaler):
        """Report skipped steps to a GradScaler so dynamic loss scaling
        decays on guard-skipped updates too."""
        self._scaler = scaler
        return self

    @staticmethod
    def is_finite(value):
        """True iff every element of ``value`` (Tensor/array/scalar) is
        finite. Forces a host sync — callers already need the loss on host
        for logging, so this is not an extra device round-trip in practice."""
        arr = np.asarray(value.numpy() if hasattr(value, 'numpy') else value)
        return bool(np.isfinite(arr).all())

    def check(self, loss):
        """Record one step; returns True when the step must be SKIPPED."""
        self.total_steps += 1
        if self.is_finite(loss):
            self.consecutive_skips = 0
            return False
        self.skipped_steps += 1
        self.consecutive_skips += 1
        if _obs.enabled():
            _obs.counter('nan_guard.skips').inc()
            _obs.event('nan_guard.skip', step=self.total_steps,
                       skipped=self.skipped_steps,
                       consecutive=self.consecutive_skips)
        if self._scaler is not None and self._scaler.is_enable():
            self._scaler.mark_found_inf()
        if self._verbose:
            import warnings
            warnings.warn(
                "NanGuard: non-finite loss at step %d — skipping the "
                "update (%d skipped so far, %d consecutive)"
                % (self.total_steps, self.skipped_steps,
                   self.consecutive_skips))
        if self.consecutive_skips >= self.max_consecutive_skips:
            _obs.event('nan_guard.abort', step=self.total_steps,
                       consecutive=self.consecutive_skips)
            err = NanStepError(
                "NanGuard: %d consecutive non-finite steps (limit %d) — "
                "the run is diverging; lower the learning rate or inspect "
                "the data pipeline" % (self.consecutive_skips,
                                       self.max_consecutive_skips))
            # black box: the run is about to die — dump the flight ring
            # (always-on, telemetry or not) so the post-mortem has the
            # last seconds of skip events and counters
            _obs.flight.dump('nan_abort', exc=err,
                             extra={'step': self.total_steps,
                                    'consecutive': self.consecutive_skips})
            raise err
        return True

    def absorb_device_counts(self, total_steps, skipped_steps, consecutive,
                             mark_scaler=True, raise_on_limit=True,
                             peak_consecutive=None):
        """Adopt counters maintained in-graph by the engine's ``lax.cond``
        NaN guard (engine.build_train_step keeps skip bookkeeping on
        device so steady-state steps never sync the host; the caller
        reconciles at its log cadence).

        Emits the same telemetry/warnings as :meth:`check` for the steps
        skipped since the last reconcile, reports them to an attached
        ``GradScaler`` unless the engine already folded the scaler update
        into the graph (``mark_scaler=False``), and enforces the same
        ``NanStepError`` consecutive-limit abort — judged on
        ``peak_consecutive`` (the running MAX of the streak between
        reconciles) so a limit-length streak that happened to end before
        this sync still aborts, exactly as the eager guard would have
        mid-streak. Returns the number of newly observed skips.
        """
        new_skips = max(int(skipped_steps) - self.skipped_steps, 0)
        self.total_steps = int(total_steps)
        self.skipped_steps = int(skipped_steps)
        self.consecutive_skips = int(consecutive)
        if new_skips:
            if _obs.enabled():
                _obs.counter('nan_guard.skips').inc(new_skips)
                _obs.event('nan_guard.skip', step=self.total_steps,
                           skipped=self.skipped_steps,
                           consecutive=self.consecutive_skips)
            if mark_scaler and self._scaler is not None and \
                    self._scaler.is_enable():
                for _ in range(new_skips):
                    self._scaler.mark_found_inf()
            if self._verbose:
                import warnings
                warnings.warn(
                    "NanGuard: %d non-finite step(s) skipped in-graph by "
                    "step %d (%d skipped so far, %d consecutive)"
                    % (new_skips, self.total_steps, self.skipped_steps,
                       self.consecutive_skips))
        worst = max(self.consecutive_skips,
                    int(peak_consecutive
                        if peak_consecutive is not None else 0))
        if raise_on_limit and worst >= self.max_consecutive_skips:
            _obs.event('nan_guard.abort', step=self.total_steps,
                       consecutive=worst)
            err = NanStepError(
                "NanGuard: %d consecutive non-finite steps (limit %d) — "
                "the run is diverging; lower the learning rate or inspect "
                "the data pipeline" % (worst, self.max_consecutive_skips))
            _obs.flight.dump('nan_abort', exc=err,
                             extra={'step': self.total_steps,
                                    'consecutive': worst})
            raise err
        return new_skips

    def state_dict(self):
        return {'skipped_steps': self.skipped_steps,
                'consecutive_skips': self.consecutive_skips,
                'total_steps': self.total_steps}

    def load_state_dict(self, sd):
        self.skipped_steps = int(sd.get('skipped_steps', 0))
        self.consecutive_skips = int(sd.get('consecutive_skips', 0))
        self.total_steps = int(sd.get('total_steps', 0))
