"""Preemption (SIGTERM) handling for training loops.

TPU fleet schedulers preempt with SIGTERM and a grace window; the default
Python behavior (immediate KeyboardInterrupt-style death) loses everything
since the last checkpoint. ``PreemptionGuard`` converts the signal into a
cooperative flag the training loop polls at step boundaries, so the loop can
checkpoint and exit cleanly inside the grace window.

Signal handlers can only be installed from the main thread; elsewhere the
guard degrades to an inert flag (``installed`` stays False) instead of
raising, so worker-thread training remains usable.
"""
import signal
import threading
import warnings

__all__ = ['PreemptionGuard']


class PreemptionGuard:
    def __init__(self, signals=(signal.SIGTERM,), on_preempt=None):
        self._signals = tuple(signals)
        self._on_preempt = on_preempt
        self._prev = {}
        self.preempted = False
        self.installed = False

    def _handler(self, signum, frame):
        self.preempted = True
        if self._on_preempt is not None:
            self._on_preempt(signum)

    def install(self):
        if self.installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            warnings.warn(
                "PreemptionGuard: not on the main thread — signal handlers "
                "cannot be installed; preemption will not be caught")
            return self
        for s in self._signals:
            self._prev[s] = signal.signal(s, self._handler)
        self.installed = True
        return self

    def uninstall(self):
        if not self.installed:
            return
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):   # interpreter shutting down
                pass
        self._prev.clear()
        self.installed = False

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
