"""Bounded retry with exponential backoff + jitter.

One decorator for every transient-failure site in the tree (downloads,
coordinator bring-up, HDFS shell-outs) so backoff policy lives in one place
instead of ad-hoc while-loops. Stdlib-only.
"""
import functools
import random
import time

from .. import observability as _obs

__all__ = ['retry', 'RetryError', 'backoff_delay']

# seam for tests/faultinject: patch to a recorder to assert backoff schedules
# without real sleeping
_sleep = time.sleep


def backoff_delay(attempt, backoff=0.1, factor=2.0, max_backoff=30.0,
                  jitter=0.5):
    """Delay (seconds) before 1-indexed ``attempt`` under the same policy
    the :func:`retry` decorator applies: ``backoff * factor**(attempt-1)``
    capped at ``max_backoff``, jittered uniformly in ``[1-j, 1+j]``.

    Public so other backoff consumers (the serving router's circuit-breaker
    cooldown, supervisor relaunch pacing) share ONE backoff curve instead
    of each growing a private exponential."""
    delay = min(backoff * (factor ** (max(1, int(attempt)) - 1)), max_backoff)
    if jitter:
        delay *= 1.0 + random.uniform(-jitter, jitter)
    return delay


class RetryError(RuntimeError):
    """All attempts failed. ``last_exception`` holds the final cause and
    ``attempts`` how many calls were made."""

    def __init__(self, message, last_exception=None, attempts=0):
        super().__init__(message)
        self.last_exception = last_exception
        self.attempts = attempts


def retry(max_attempts=3, backoff=0.1, factor=2.0, max_backoff=30.0,
          jitter=0.5, timeout=None, retry_on=(OSError, ConnectionError,
                                              TimeoutError), on_retry=None,
          reraise=False):
    """Decorator: call the function up to ``max_attempts`` times.

    Delay before attempt k (1-indexed) is ``backoff * factor**(k-1)``, capped
    at ``max_backoff``, multiplied by a uniform jitter in
    ``[1 - jitter, 1 + jitter]`` so a preempted TPU fleet does not stampede a
    coordinator in lockstep. ``timeout`` bounds total elapsed time across
    attempts (seconds, measured from the first call). Only exceptions matching
    ``retry_on`` are retried; anything else propagates immediately.
    ``on_retry(attempt, exc, delay)`` is invoked before each sleep.
    ``reraise=True`` re-raises the final exception unchanged on exhaustion
    (for callers whose API contract names specific exception types) instead
    of wrapping it in :class:`RetryError`.
    """
    if max_attempts < 1:
        raise ValueError("max_attempts must be >= 1")
    retry_on = tuple(retry_on) if isinstance(retry_on, (list, tuple, set)) \
        else (retry_on,)

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            start = time.monotonic()
            last = None
            for attempt in range(1, max_attempts + 1):
                try:
                    return fn(*args, **kwargs)
                except retry_on as e:
                    last = e
                    if attempt == max_attempts:
                        break
                    delay = backoff_delay(attempt, backoff=backoff,
                                          factor=factor,
                                          max_backoff=max_backoff,
                                          jitter=jitter)
                    if timeout is not None and \
                            time.monotonic() - start + delay > timeout:
                        if reraise:
                            raise e
                        raise RetryError(
                            "%s: retry timeout (%.1fs) exhausted after %d "
                            "attempt(s): %s" % (getattr(fn, '__name__', fn),
                                                timeout, attempt, e),
                            last_exception=e, attempts=attempt) from e
                    if on_retry is not None:
                        on_retry(attempt, e, delay)
                    if _obs.enabled():
                        _obs.counter('retry.attempts').inc()
                        _obs.event('retry.attempt',
                                   fn=getattr(fn, '__name__', str(fn)),
                                   attempt=attempt, delay=round(delay, 3),
                                   error=repr(e))
                    _retry_sleep(delay)
            if reraise:
                raise last
            raise RetryError(
                "%s: all %d attempt(s) failed: %s"
                % (getattr(fn, '__name__', fn), max_attempts, last),
                last_exception=last, attempts=max_attempts) from last
        return wrapper
    return deco


def _retry_sleep(delay):
    # indirect so tests patching retry._sleep take effect after decoration
    _sleep(delay)
