"""Bounded waits + liveness: the primitives that keep one dead participant
from hanging the whole job.

Every blocking wait in library code (queue gets between DataLoader workers
and the consumer, thread/process joins, child-process waits) goes through
these helpers instead of the unbounded stdlib calls, so a producer that
died without posting its sentinel — or a child that will never exit — turns
into a loud, diagnosable ``WatchdogTimeout`` instead of a silent stall.
graftlint rule GL012 enforces the discipline tree-wide.

Three pieces:

- ``bounded_get(q, ...)`` — ``queue.Queue.get`` in short ticks with an
  optional overall deadline and an optional ``alive()`` probe; dead
  producers are detected within one tick even under a long deadline;
- ``join_thread`` / ``join_proc`` / ``wait_proc`` — tick-based joins that
  stay interruptible and report (rather than swallow) expiry;
- ``Heartbeat`` — a daemon thread touching a file every ``interval``
  seconds; supervisors read the mtime (``heartbeat_age``) to distinguish a
  busy rank from a wedged one.

All helpers are stdlib-only and safe to import from worker processes.
"""
import os
import queue
import threading
import time

__all__ = ['WatchdogTimeout', 'bounded_get', 'join_thread', 'join_proc',
           'wait_proc', 'Heartbeat', 'heartbeat_age', 'DEFAULT_TICK']

# Tick between liveness probes: short enough that a dead producer is
# reported promptly, long enough that the poll is free next to any real
# batch-assembly work.
DEFAULT_TICK = 0.1


# rate limiter for the watchdog's flight-recorder dumps: every timeout is
# RECORDED in the ring, but the disk dump is throttled — a client polling
# result(timeout=0.1) must not fsync a document per miss
_FLIGHT_DUMP_EVERY_S = 5.0
_last_flight_dump = [0.0]


class WatchdogTimeout(RuntimeError):
    """A bounded wait expired (or every producer died) before the item
    arrived. ``.what`` names the wait; ``.waited`` is the elapsed seconds.

    Construction records into the observability flight recorder and dumps
    its black box (best-effort, always-on, rate-limited): a watchdog
    firing usually means something is wedged or dead, and the ring's last
    seconds are the evidence a post-mortem needs. The dump goes to a
    watchdog-specific file (``flight_rank<R>_watchdog.json``) so a caught,
    routine client timeout never clobbers the primary black box a real
    crash (worker exception, NaN abort) wrote. The import is lazy so this
    module stays safe to import from bare worker processes."""

    def __init__(self, message, what='wait', waited=0.0):
        super().__init__(message)
        self.what = what
        self.waited = waited
        try:
            from ..observability import flight
            flight.record('watchdog_timeout', what=what,
                          waited=round(waited, 3))
            now = time.monotonic()
            if now - _last_flight_dump[0] >= _FLIGHT_DUMP_EVERY_S:
                _last_flight_dump[0] = now
                flight.dump(
                    'watchdog_timeout', exc=self,
                    extra={'what': what, 'waited': round(waited, 3)},
                    filename=f'flight_rank{flight.rank_id()}_watchdog.json')
        except Exception:
            pass   # the black box must never mask the timeout itself


def bounded_get(q, timeout=None, alive=None, what='queue item',
                tick=DEFAULT_TICK, on_dead=None):
    """``q.get()`` that cannot hang forever.

    Polls in ``tick``-second slices. Raises ``WatchdogTimeout`` when

    - ``timeout`` seconds pass with no item (``timeout=None`` = no overall
      deadline; the liveness probe still applies), or
    - ``alive()`` returns False while the queue is empty — the producers
      are gone and the sentinel/item can never arrive. ``on_dead()`` (when
      given) is called first and may raise a more specific error.
    """
    deadline = None if not timeout else time.monotonic() + timeout
    start = time.monotonic()
    while True:
        step = tick if deadline is None else \
            max(min(tick, deadline - time.monotonic()), 0.001)
        try:
            return q.get(timeout=step)
        except queue.Empty:
            pass
        waited = time.monotonic() - start
        if alive is not None and not alive():
            # one more bounded drain: the producer may have posted and died
            # between our get() and the probe (mp.Queue flushes through a
            # feeder thread, so allow a short grace period)
            try:
                return q.get(timeout=max(tick, 0.2))
            except queue.Empty:
                pass
            if on_dead is not None:
                on_dead()
            raise WatchdogTimeout(
                f"watchdog: every producer of {what} died without posting "
                f"it (waited {waited:.1f}s) — a worker crashed before its "
                "done sentinel", what=what, waited=waited)
        if deadline is not None and time.monotonic() >= deadline:
            raise WatchdogTimeout(
                f"watchdog: no {what} within {timeout:.1f}s "
                "(producers alive but not producing — deadlocked or hung "
                "worker)", what=what, waited=waited)


def join_thread(t, timeout=None, tick=0.5):
    """Join a thread in ticks (stays signal-interruptible). Returns True
    when the thread finished, False when ``timeout`` expired first
    (``timeout=None`` waits indefinitely, but never in one blocking call)."""
    deadline = None if timeout is None else time.monotonic() + timeout
    while t.is_alive():
        t.join(tick)
        if deadline is not None and time.monotonic() >= deadline \
                and t.is_alive():
            return False
    return True


def join_proc(p, timeout=None, tick=0.25):
    """Tick-based join for a multiprocessing.Process-like object (join/
    is_alive). Same contract as ``join_thread``."""
    deadline = None if timeout is None else time.monotonic() + timeout
    while p.is_alive():
        p.join(tick)
        if deadline is not None and time.monotonic() >= deadline \
                and p.is_alive():
            return False
    return True


def wait_proc(popen, timeout=None, tick=0.25):
    """Tick-based ``subprocess.Popen.wait``. Returns the exit code, or
    None when ``timeout`` expired with the child still running."""
    import subprocess
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        try:
            return popen.wait(tick)
        except subprocess.TimeoutExpired:
            if deadline is not None and time.monotonic() >= deadline:
                return None


class Heartbeat:
    """Touch ``path`` every ``interval`` seconds from a daemon thread.

    A supervisor that can see the file distinguishes "rank busy in a long
    XLA compile" (fresh heartbeat) from "rank wedged in a collective that
    will never complete" (stale heartbeat) — liveness, not just existence.
    """

    def __init__(self, path, interval=0.5):
        self.path = os.fspath(path)
        self.interval = float(interval)
        self._stop = threading.Event()
        self._thread = None

    def _beat_once(self):
        try:
            with open(self.path, 'a'):
                os.utime(self.path, None)
        except OSError:
            pass   # result dir vanished (parent cleanup) — nothing to report

    def _run(self):
        while not self._stop.wait(self.interval):
            self._beat_once()

    def start(self):
        if self._thread is None:
            self._beat_once()
            self._thread = threading.Thread(
                target=self._run, name='paddle-tpu-heartbeat', daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            join_thread(self._thread, timeout=self.interval * 4)
            self._thread = None


def heartbeat_age(path):
    """Seconds since the heartbeat file was last touched, or None when it
    was never written (rank died before its first beat, or no heartbeat
    was configured)."""
    try:
        # graftlint: disable=GL011 — comparing against a file mtime needs
        # the wall clock, not a telemetry duration
        return max(time.time() - os.path.getmtime(path), 0.0)
    except OSError:
        return None
