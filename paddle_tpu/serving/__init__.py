"""paddle_tpu.serving: multi-tenant inference with continuous batching.

The "millions of users" layer over the PR 1–5 stack: exported models
(``jit.load`` / ``inference.load_inference_model``) are kept warm in the
compiled-program caches and served under load with

- **fixed bucket shapes** (``bucketing``) — a closed compiled-shape set,
  so steady-state traffic never traces or compiles (``jax.compiles`` flat
  after ``warmup()``; graftlint GL013 lints for violations statically);
- **iteration-level continuous batching** (``runners``) — one-shot models
  re-pack the queue every batch; generative models join/leave the KV
  cache per decode step: by default a **paged** cache (``paged_kv`` /
  ``paged_runner``: block tables over a refcounted page pool, prefix
  sharing of identical prompt prefixes, chunked prefill for long
  prompts, speculative decoding via a draft spec), with the fixed-slot
  cache (``kv_cache``) retained as the memory baseline;
- **production edges** (``scheduler``) — bounded admission queues with
  429-style shedding, per-request deadlines (expired work is dropped, not
  run), watchdog-bounded client waits;
- **tenancy + elasticity** (``admission`` / ``autoscaler``) — per-tenant
  weighted-fair (deficit-round-robin) admission with token-bucket quotas
  (over-quota submits shed with reason ``quota``), per-tenant SLO burn
  isolation, and an autoscaler that grows the router fleet on sustained
  SLO burn (warm, zero-compile via the artifact tier) and shrinks it
  through ``drain()`` with zero aborted in-flight work;
- **telemetry** on the PR 3 spine — ``serving.*`` counters, latency /
  queue-wait / batch-occupancy histograms, per-request events
  (``tools/telemetry_dump.py --serving`` summarizes them).

Quick start (docs/SERVING.md has the full guide)::

    engine = serving.ServingEngine(queue_capacity=64)
    ep = engine.register('clf', layer=model,
                         example={'x': np.zeros((16,), np.float32)})
    engine.warmup()          # compile every bucket now
    engine.start()           # background worker thread
    resp = ep.predict({'x': features}, deadline_ms=50)
"""
from .admission import (DEFAULT_TENANT, QuotaExceededError, TenantArbiter,
                        TenantPolicy, WeightedFairQueue, tenant_stats)
from .autoscaler import FleetAutoscaler
from .bucketing import (DEFAULT_BATCH_BUCKETS, BucketSpec, pad_to_bucket,
                        select_bucket, stack_examples)
from .engine import Endpoint, EngineDeadError, ServingEngine
from .fleet_supervisor import FleetSupervisor
from .kv_cache import GenerativeSpec, TinyCausalLM
from .paged_kv import (PageAllocator, PagesExhaustedError, PrefixCache,
                       chain_hashes)
from .paged_runner import PagedGenerativeRunner
from .router import (CircuitBreaker, FleetOverloadError, FleetPending,
                     FleetRouter, NoHealthyReplicaError, ReplicaError,
                     ReplicaHandle, RouterPolicy)
from .runners import BatchRunner, GenerativeRunner
from .scheduler import (AdmissionQueue, PendingRequest, QueueFullError,
                        Request, Response, STATUS_CANCELLED,
                        STATUS_DEADLINE, STATUS_ERROR, STATUS_OK)
from . import (admission, autoscaler, bucketing, engine,  # noqa: F401
               fleet_supervisor, kv_cache, paged_kv, paged_runner, router,
               runners, scheduler)

__all__ = [
    'ServingEngine', 'Endpoint', 'EngineDeadError',
    'FleetRouter', 'RouterPolicy', 'ReplicaHandle', 'CircuitBreaker',
    'FleetPending', 'ReplicaError', 'NoHealthyReplicaError',
    'FleetOverloadError', 'FleetSupervisor', 'FleetAutoscaler',
    'TenantPolicy', 'TenantArbiter', 'WeightedFairQueue',
    'QuotaExceededError', 'DEFAULT_TENANT', 'tenant_stats',
    'BucketSpec', 'DEFAULT_BATCH_BUCKETS', 'select_bucket', 'pad_to_bucket',
    'stack_examples',
    'GenerativeSpec', 'TinyCausalLM',
    'BatchRunner', 'GenerativeRunner', 'PagedGenerativeRunner',
    'PageAllocator', 'PagesExhaustedError', 'PrefixCache', 'chain_hashes',
    'AdmissionQueue', 'PendingRequest', 'QueueFullError', 'Request',
    'Response', 'STATUS_OK', 'STATUS_DEADLINE', 'STATUS_ERROR',
    'STATUS_CANCELLED',
]
