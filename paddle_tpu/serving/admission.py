"""Tenancy for the serving tier: policies, quotas, weighted-fair pops.

One serving tier, many tenants (docs/SERVING.md, "Tenancy +
autoscaling"). Without this layer any single tenant can flood the shared
``AdmissionQueue`` and starve every neighbor behind the same engine or
router. Three pieces close that hole:

- **``TenantPolicy``** — the per-tenant contract: a scheduling ``weight``
  (its share of pop bandwidth), an optional token-bucket ``rate``/``burst``
  quota (requests/s it may *submit*; beyond it submits shed immediately),
  a ``priority_floor`` the router's shed ladder enforces at level 1, and
  optional cost budgets (``cost_budget_flops`` / ``hbm_budget_bytes``)
  charged from the PR 13 cost ledger's per-program numbers.
- **``TenantArbiter``** — the policy registry + quota gate in front of
  admission. ``check(tenant, model)`` either charges one token or raises
  ``QuotaExceededError`` (a shaped ``QueueFullError`` with reason
  ``'quota'`` — the third shed reason beside ``queue_full`` /
  ``page_exhaustion``), so a storming tenant is shed at the front door
  while nothing of its flood ever reaches the queue.
- **``WeightedFairQueue``** — a drop-in ``AdmissionQueue`` holding one
  FIFO per tenant and popping in **deficit-round-robin** order: each
  visit grants a tenant ``quantum * weight`` deficit, each popped request
  costs 1, an emptied tenant forfeits its residue. A tenant with weight 2
  drains twice as fast as a tenant with weight 1, deterministically, and
  a storming tenant consumes only its share of batch slots. Strict FIFO
  *within* a tenant, and an ``admit``-declined head (the paged runner's
  KV-page gate) stops the whole pop — no head-of-line jumping.

Per-tenant accounting is module-level and always-on (the ``_Stats``
discipline, like ``observability.slo``): plain dict math, mirrored to
``serving.tenant.*`` labeled counters and a cumulative
``serving.tenant_stats`` event while telemetry is enabled. Burn is
tracked per (tenant, model) against the model's SLO objective, so one
tenant's violations never move a neighbor's error-budget burn.
"""
import collections
import threading

from ..observability import events, registry, state
from ..observability import slo as _slo
from ..observability.timing import Stopwatch
from .scheduler import AdmissionQueue, QueueFullError

__all__ = ['DEFAULT_TENANT', 'QuotaExceededError', 'TenantPolicy',
           'TenantArbiter', 'WeightedFairQueue', 'record_completion',
           'record_shed', 'tenant_stats', 'tenant_burn_rates',
           'reset_tenant_stats']

DEFAULT_TENANT = 'default'

#: deficit granted per DRR visit, scaled by the tenant's weight. Each
#: popped request costs 1.0, so a weight-2 tenant pops two requests per
#: round for a weight-1 tenant's one.
DRR_QUANTUM = 1.0


class QuotaExceededError(QueueFullError):
    """A tenant's token-bucket / cost budget is exhausted: shed at submit.

    A shaped ``QueueFullError`` (so router failover and client backoff
    paths treat it as a shed, not a crash) with ``reason == 'quota'`` —
    but unlike ``queue_full``/``page_exhaustion`` it is **tenant-global**:
    retrying another replica cannot help, the tenant itself is over its
    contract. The router therefore re-raises it to the client instead of
    burning failover attempts.
    """

    def __init__(self, model, tenant, rate=None, burst=None, detail='rate'):
        RuntimeError.__init__(
            self,
            f"serving: tenant {tenant!r} over {detail} quota for model "
            f"{model!r} (rate={rate}, burst={burst}) — request shed "
            "(quota); retry with backoff")
        self.model = model
        self.capacity = burst
        self.reason = 'quota'
        self.tenant = tenant
        self.rate = rate
        self.burst = burst
        self.detail = detail


class TenantPolicy:
    """The per-tenant serving contract.

    ``weight`` — relative share of DRR pop bandwidth (default 1.0).
    ``rate``/``burst`` — token-bucket submit quota in requests/s with a
    ``burst`` bucket cap (default ``max(1, round(rate))``); ``rate=None``
    means unmetered. ``priority_floor`` — at shed-ladder level 1 the
    router rejects this tenant's requests whose priority is *below* the
    floor (a premium tenant sets 0 and nothing of its traffic sheds at
    level 1; a batch tenant sets a high floor and sheds first).
    ``cost_budget_flops``/``hbm_budget_bytes`` — optional cumulative cost
    budgets; ``TenantArbiter.charge`` spends against them (source: the
    PR 13 cost ledger's per-program flops/peak-HBM numbers).
    """

    __slots__ = ('name', 'weight', 'rate', 'burst', 'priority_floor',
                 'cost_budget_flops', 'hbm_budget_bytes')

    def __init__(self, name, weight=1.0, rate=None, burst=None,
                 priority_floor=0, cost_budget_flops=None,
                 hbm_budget_bytes=None):
        if not name:
            raise ValueError("tenant policy needs a name")
        weight = float(weight)
        if weight <= 0:
            raise ValueError(
                f"tenant {name!r}: weight must be > 0, got {weight}")
        if rate is not None:
            rate = float(rate)
            if rate <= 0:
                raise ValueError(
                    f"tenant {name!r}: rate must be > 0, got {rate}")
        if burst is None:
            burst = max(1, round(rate)) if rate is not None else None
        elif burst < 1:
            raise ValueError(
                f"tenant {name!r}: burst must be >= 1, got {burst}")
        self.name = str(name)
        self.weight = weight
        self.rate = rate
        self.burst = None if burst is None else int(burst)
        self.priority_floor = int(priority_floor)
        self.cost_budget_flops = cost_budget_flops
        self.hbm_budget_bytes = hbm_budget_bytes

    def __repr__(self):
        return (f"TenantPolicy({self.name!r}, weight={self.weight}, "
                f"rate={self.rate}, burst={self.burst}, "
                f"priority_floor={self.priority_floor})")


class TenantArbiter:
    """Policy registry + quota gate. Shared by an engine (front door) or a
    router (fleet front door) — never both at once, or tokens are charged
    twice per request.

    ``clock`` is a zero-arg seconds callable for token refill (default: a
    fresh ``Stopwatch``'s elapsed — the GL011-sanctioned monotonic clock).
    Tests inject a virtual clock so refill is deterministic.
    """

    def __init__(self, policies=None, clock=None):
        self._policies = {}
        self._buckets = {}     # tenant -> [tokens, last_refill_s]
        self._spend = {}       # tenant -> {'flops': float, 'hbm_bytes': f}
        self._lock = threading.Lock()
        self._clock = clock if clock is not None else Stopwatch().elapsed
        for p in (policies or []):
            self.set_policy(p)

    def set_policy(self, policy):
        if not isinstance(policy, TenantPolicy):
            raise TypeError(f"expected TenantPolicy, got {type(policy)}")
        with self._lock:
            self._policies[policy.name] = policy
            # a fresh bucket starts full: burst is the contract's headroom
            if policy.rate is not None:
                self._buckets[policy.name] = [float(policy.burst),
                                              float(self._clock())]
            else:
                self._buckets.pop(policy.name, None)
        return policy

    def policy(self, tenant):
        """The tenant's policy; unknown tenants get the default contract
        (weight 1, unmetered, floor 0) without registering it."""
        with self._lock:
            pol = self._policies.get(tenant)
        return pol or TenantPolicy(tenant or DEFAULT_TENANT)

    def policies(self):
        with self._lock:
            return dict(self._policies)

    def weight(self, tenant):
        return self.policy(tenant).weight

    def priority_floor(self, tenant):
        return self.policy(tenant).priority_floor

    def check(self, tenant, model):
        """Charge one token (and the cost budgets) or shed.

        Raises ``QuotaExceededError`` when the tenant's token bucket is
        empty or a cost budget is spent. On success the token is consumed
        — call exactly once per submit, at the front door.
        """
        tenant = tenant or DEFAULT_TENANT
        pol = self.policy(tenant)
        with self._lock:
            spend = self._spend.get(tenant, {})
            if pol.cost_budget_flops is not None and \
                    spend.get('flops', 0.0) >= pol.cost_budget_flops:
                raise QuotaExceededError(model, tenant, rate=pol.rate,
                                         burst=pol.burst, detail='flops')
            if pol.hbm_budget_bytes is not None and \
                    spend.get('hbm_bytes', 0.0) >= pol.hbm_budget_bytes:
                raise QuotaExceededError(model, tenant, rate=pol.rate,
                                         burst=pol.burst, detail='hbm')
            if pol.rate is not None:
                bucket = self._buckets.setdefault(
                    tenant, [float(pol.burst), float(self._clock())])
                now = float(self._clock())
                tokens = min(float(pol.burst),
                             bucket[0] + (now - bucket[1]) * pol.rate)
                bucket[1] = now
                if tokens < 1.0:
                    bucket[0] = tokens
                    raise QuotaExceededError(model, tenant, rate=pol.rate,
                                             burst=pol.burst)
                bucket[0] = tokens - 1.0
        if state.enabled():
            registry.counter('serving.tenant.submitted',
                             labels={'tenant': tenant}).inc()

    def charge(self, tenant, flops=0.0, hbm_bytes=0.0):
        """Spend against the tenant's cost budgets (source: the cost
        ledger's per-program flops/peak-HBM for the model it ran)."""
        tenant = tenant or DEFAULT_TENANT
        with self._lock:
            spend = self._spend.setdefault(
                tenant, {'flops': 0.0, 'hbm_bytes': 0.0})
            spend['flops'] += float(flops)
            spend['hbm_bytes'] += float(hbm_bytes)
            return dict(spend)

    def spend(self, tenant):
        with self._lock:
            return dict(self._spend.get(tenant,
                                        {'flops': 0.0, 'hbm_bytes': 0.0}))

    def tokens(self, tenant):
        """Current token balance (after refill), or None when unmetered."""
        pol = self.policy(tenant)
        if pol.rate is None:
            return None
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                return float(pol.burst)
            return min(float(pol.burst),
                       bucket[0] + (float(self._clock()) - bucket[1])
                       * pol.rate)

    def stats(self):
        out = {}
        for name, pol in self.policies().items():
            out[name] = {'weight': pol.weight, 'rate': pol.rate,
                         'burst': pol.burst,
                         'priority_floor': pol.priority_floor,
                         'tokens': self.tokens(name)}
        return out


class WeightedFairQueue(AdmissionQueue):
    """``AdmissionQueue`` with one FIFO per tenant and DRR pop order.

    Same interface and capacity semantics (capacity bounds the *total*
    across tenants), so runners need no changes — ``pop_ready`` /
    ``pop_ready_while`` simply interleave tenants by weight instead of
    global arrival order. The DRR cursor persists across pops, so
    fairness holds across ``pump()`` steps, not just within one.
    """

    def __init__(self, model, capacity=256, arbiter=None):
        super().__init__(model, capacity)
        self._arbiter = arbiter
        self._qs = {}                  # tenant -> deque
        self._deficit = {}
        self._ring = []                # visit order: first-push order
        self._cursor = 0
        self._n = 0

    def _weight(self, tenant):
        return self._arbiter.weight(tenant) if self._arbiter else 1.0

    def _tenant_of(self, req):
        return getattr(req, 'tenant', None) or DEFAULT_TENANT

    def _q_for(self, tenant):
        dq = self._qs.get(tenant)
        if dq is None:
            dq = self._qs[tenant] = collections.deque()
            self._deficit[tenant] = 0.0
            self._ring.append(tenant)
        return dq

    def __len__(self):
        return self._n

    def tenants_queued(self):
        """{tenant: queued count} for every tenant with a backlog."""
        with self._lock:
            return {t: len(dq) for t, dq in self._qs.items() if dq}

    def push(self, req):
        with self._lock:
            if self._n >= self.capacity:
                raise QueueFullError(self.model, self.capacity)
            self._q_for(self._tenant_of(req)).append(req)
            self._n += 1

    def push_front(self, req):
        with self._lock:
            self._q_for(self._tenant_of(req)).appendleft(req)
            self._n += 1

    def pop_ready_while(self, admit, max_n):
        ready, expired = [], []
        with self._lock:
            idle_visits = 0
            while self._n and len(ready) < max_n:
                if not self._ring:
                    break
                self._cursor %= len(self._ring)
                tenant = self._ring[self._cursor]
                dq = self._qs.get(tenant)
                if not dq:
                    # an emptied tenant forfeits its residue (classic DRR)
                    self._deficit[tenant] = 0.0
                    self._cursor += 1
                    idle_visits += 1
                    if idle_visits >= len(self._ring):
                        break
                    continue
                idle_visits = 0
                self._deficit[tenant] += DRR_QUANTUM * self._weight(tenant)
                blocked = False
                while dq and self._deficit[tenant] >= 1.0 \
                        and len(ready) < max_n:
                    req = dq[0]
                    if req.expired():
                        expired.append(dq.popleft())
                        self._n -= 1
                        continue
                    if admit is not None and not admit(req):
                        blocked = True
                        break
                    ready.append(dq.popleft())
                    self._n -= 1
                    self._deficit[tenant] -= 1.0
                if not dq:
                    self._deficit[tenant] = 0.0
                self._cursor += 1
                if blocked:
                    # an admit-declined head (KV pages) stalls the WHOLE
                    # pop — skipping to another tenant would hand the
                    # blocked tenant's batch slots to its neighbors and
                    # starve it exactly when it is resource-pressured
                    break
        for r in ready + expired:
            r.queue_ms = r.sw.elapsed_ms()
        return ready, expired

    def remove(self, req):
        with self._lock:
            tenant = self._tenant_of(req)
            order = [self._qs[tenant]] if tenant in self._qs else []
            order += [dq for t, dq in self._qs.items() if t != tenant]
            for dq in order:
                try:
                    dq.remove(req)
                except ValueError:
                    continue
                self._n -= 1
                return True
        return False

    def reap_expired(self):
        expired = []
        with self._lock:
            for dq in self._qs.values():
                live = [r for r in dq if not r.expired()]
                if len(live) != len(dq):
                    expired.extend(r for r in dq if r.expired())
                    dq.clear()
                    dq.extend(live)
            self._n -= len(expired)
        for r in expired:
            r.queue_ms = r.sw.elapsed_ms()
        return expired

    def drain(self):
        with self._lock:
            out = []
            for tenant in self._ring:
                out.extend(self._qs[tenant])
                self._qs[tenant].clear()
            self._n = 0
        return out


# -- per-tenant accounting (always-on tallies, slo.py discipline) -----------

_acct_lock = threading.Lock()
_tallies = {}       # tenant -> {'requests', 'violations'}
_burn_keys = {}     # (tenant, model) -> {'requests', 'violations'}
_sheds = {}         # tenant -> {reason: count}


def record_completion(req, status, latency_ms):
    """Attribute one completed request to its tenant.

    Called from ``runners.finish_request`` for every request carrying a
    tenant. Judges the request against the *model's* SLO objective but
    tallies per (tenant, model), so ``tenant_burn_rates`` isolates each
    tenant's burn — one tenant's violations never move a neighbor's.
    """
    tenant = getattr(req, 'tenant', None)
    if not tenant:
        return None
    obj = _slo.objective(req.model)
    violated = status != 'ok' or (
        obj is not None and float(latency_ms) > obj['target_ms'])
    with _acct_lock:
        t = _tallies.setdefault(tenant, {'requests': 0, 'violations': 0})
        t['requests'] += 1
        b = _burn_keys.setdefault((tenant, req.model),
                                  {'requests': 0, 'violations': 0})
        b['requests'] += 1
        if violated:
            t['violations'] += 1
            b['violations'] += 1
        b_requests, b_violations = b['requests'], b['violations']
    burn = None
    if obj is not None:
        budget = max(1.0 - obj['objective'], 1e-9)
        burn = (b_violations / b_requests) / budget
    if state.enabled():
        lbl = {'tenant': str(tenant)}
        registry.counter('serving.tenant.requests', labels=lbl).inc()
        registry.histogram('serving.tenant.latency_ms', labels=lbl) \
            .observe(float(latency_ms))
        if violated:
            registry.counter('serving.tenant.violations', labels=lbl).inc()
        if burn is not None:
            registry.gauge('serving.tenant.burn_rate',
                           labels=lbl).set(round(burn, 4))
        # the cumulative ledger event (last-wins for consumers): only
        # once traffic is actually multi-tenant / shedding — single-
        # tenant default traffic keeps its event stream lean
        if tenant != DEFAULT_TENANT or len(_tallies) > 1 or _sheds:
            events.emit('serving.tenant_stats', tenants=tenant_stats())
    return burn


def record_shed(tenant, reason):
    """Attribute one shed to its tenant (reason: ``queue_full`` /
    ``page_exhaustion`` / ``quota``). Called by the engine/router shed
    paths beside their unlabeled ``serving.shed.*`` counters."""
    tenant = tenant or DEFAULT_TENANT
    with _acct_lock:
        _sheds.setdefault(tenant, {})[reason] = \
            _sheds.get(tenant, {}).get(reason, 0) + 1
    if state.enabled():
        registry.counter('serving.tenant.shed',
                         labels={'tenant': str(tenant)}).inc()
        events.emit('serving.tenant_stats', tenants=tenant_stats())


def tenant_burn_rates():
    """{tenant: worst per-model burn} over this tenant's own traffic."""
    with _acct_lock:
        items = [(k, dict(v)) for k, v in _burn_keys.items()]
    out = {}
    for (tenant, model), t in items:
        obj = _slo.objective(model)
        if obj is None or not t['requests']:
            continue
        budget = max(1.0 - obj['objective'], 1e-9)
        burn = round((t['violations'] / t['requests']) / budget, 4)
        out[tenant] = max(out.get(tenant, 0.0), burn)
    return out


def tenant_stats():
    """{tenant: {requests, violations, burn, shed: {reason: n}}} — the
    cumulative per-tenant ledger (also the ``serving.tenant_stats``
    event payload)."""
    burns = tenant_burn_rates()
    with _acct_lock:
        tenants = set(_tallies) | set(_sheds)
        out = {}
        for t in sorted(tenants):
            tal = _tallies.get(t, {'requests': 0, 'violations': 0})
            out[t] = {'requests': tal['requests'],
                      'violations': tal['violations'],
                      'burn': burns.get(t, 0.0),
                      'shed': dict(_sheds.get(t, {}))}
    return out


def reset_tenant_stats():
    with _acct_lock:
        _tallies.clear()
        _burn_keys.clear()
        _sheds.clear()
