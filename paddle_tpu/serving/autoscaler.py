"""FleetAutoscaler: SLO-driven elastic replica count behind the router.

The elasticity half of ROADMAP item 2 (docs/SERVING.md, "Tenancy +
autoscaling"). The ``FleetSupervisor`` restores capacity the fleet
*lost* (replica death); this control loop changes how much capacity the
fleet *has*, driven by the same signals mission control watches:

- **grow** when pressure is *sustained* — the peak per-model
  ``slo.burn_rate`` holds at/above ``burn_high`` (or page-exhaustion
  sheds keep arriving) for ``sustain_ticks`` consecutive observations —
  and the fleet is below ``max_replicas``. The new replica comes from
  ``replica_factory`` exactly like a supervisor relaunch: built + warmed
  under ``compilecache.use(artifact_dir)`` (scale-up against a populated
  dir is **zero-compile**) and it rejoins through the router's half-open
  probe gate, so even a cold replica meets bounded traffic first.
- **shrink** when the fleet is *calm* — burn at/below ``burn_low`` and
  no page sheds for ``sustain_ticks`` observations — and above
  ``min_replicas``. The least-loaded replica is taken out through
  ``router.drain()`` (queued + resident requests finish; zero aborted
  in-flight is the drain contract), then removed and stopped.

Flap-proofing is structural, not tuned: ``burn_low < burn_high`` is an
enforced hysteresis band (a signal value cannot demand both directions),
pressure/calm must hold for ``sustain_ticks`` *consecutive* observations
(the window resets on every action), every action starts a
``cooldown_ticks`` dead time, and the replica count is clamped to the
``[min_replicas, max_replicas]`` envelope. An oscillating signal that
alternates inside the window can therefore never sustain either
condition, and even a pathological signal moves the fleet at most once
per ``cooldown_ticks + sustain_ticks`` ticks.

Every transition lands as ``fleet.autoscale`` events +
``fleet.autoscale.*`` counters/histograms, a flight-recorder record, and
(when the PR 18 ring sampler is active) a stamped time-series sample, so
``tools/telemetry_dump.py --series`` shows the replica-count step
exactly where the burn trend crossed the band. Drive it manually with
``tick()`` (deterministic tests/benches) or as a background thread via
``start()``/``stop()``.
"""
import collections
import itertools
import threading

from .. import observability as _obs
from ..observability import slo as _slo
from ..observability.timing import Stopwatch

__all__ = ['FleetAutoscaler']


class FleetAutoscaler:
    """Grow/shrink a ``FleetRouter``'s replica set on sustained SLO burn.

    ``replica_factory(name)`` must return a ready ``ServingEngine``
    (models registered; ``start()``-ed iff the fleet runs background
    workers) — pass ``supervisor=`` to reuse a ``FleetSupervisor``'s
    factory, ``artifact_dir`` and ``warmup`` settings instead of
    repeating them. ``signal=`` overrides the default pressure signal
    with any zero-arg callable returning a burn-like float (chaos tests
    feed ``faultinject.burn_ramp``-shaped sequences through it).
    """

    def __init__(self, router, replica_factory=None, supervisor=None,
                 min_replicas=1, max_replicas=4, burn_high=1.0,
                 burn_low=0.25, shed_high=1, sustain_ticks=3,
                 cooldown_ticks=5, warmup=None, artifact_dir=None,
                 drain_timeout_s=10.0, check_interval_s=0.25, signal=None,
                 name_prefix='scale'):
        if replica_factory is None and supervisor is not None:
            replica_factory = supervisor.replica_factory
        if replica_factory is None:
            raise ValueError(
                "autoscaler: needs replica_factory= (or supervisor= to "
                "borrow one from)")
        if supervisor is not None:
            if artifact_dir is None:
                artifact_dir = supervisor.artifact_dir
            if warmup is None:
                warmup = supervisor.warmup
        self.router = router
        self.replica_factory = replica_factory
        self.supervisor = supervisor
        self.artifact_dir = artifact_dir
        self.warmup = True if warmup is None else bool(warmup)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        if self.min_replicas < 1:
            raise ValueError(
                f"autoscaler: min_replicas must be >= 1, got "
                f"{min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"autoscaler: max_replicas ({max_replicas}) < "
                f"min_replicas ({min_replicas})")
        self.burn_high = float(burn_high)
        self.burn_low = float(burn_low)
        if not self.burn_low < self.burn_high:
            raise ValueError(
                f"autoscaler: hysteresis band requires burn_low < "
                f"burn_high, got [{burn_low}, {burn_high}] — a degenerate "
                "band lets one signal value demand both directions (flap)")
        self.shed_high = int(shed_high)
        self.sustain_ticks = max(1, int(sustain_ticks))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self.drain_timeout_s = float(drain_timeout_s)
        self.check_interval_s = float(check_interval_s)
        self.signal = signal
        self.name_prefix = name_prefix
        self._history = collections.deque(maxlen=self.sustain_ticks)
        self._cooldown = 0
        self._tick = 0
        self._last_page_sheds = None
        self._names = itertools.count(1)
        self._decisions = collections.deque(maxlen=256)
        self._last_detail = None   # grow/shrink evidence for the decision
        # one actor at a time: a manual tick() racing the background loop
        # must not both act on the same observation window (reentrant:
        # tick() calls observe(), which takes it for the shed delta too)
        self._act_lock = threading.RLock()
        self._thread = None
        self._stop = threading.Event()

    # -- signal ----------------------------------------------------------
    def _page_sheds_now(self):
        """Cumulative page-exhaustion sheds across the fleet (always-on
        engine tallies — no telemetry dependency)."""
        total = 0
        for h in self.router.replicas():
            total += getattr(h.engine, '_shed_page_exhaustion', 0)
        return total

    def observe(self):
        """One observation: ``{'burn': float, 'page_sheds': int}`` —
        peak per-model SLO burn (or the injected ``signal``) plus the
        page-exhaustion-shed delta since the previous observation."""
        if self.signal is not None:
            burn = float(self.signal())
        else:
            burns = _slo.burn_rates()
            burn = max(burns.values()) if burns else 0.0
        now = self._page_sheds_now()
        with self._act_lock:
            delta = 0 if self._last_page_sheds is None \
                else max(0, now - self._last_page_sheds)
            self._last_page_sheds = now
        return {'burn': burn, 'page_sheds': delta}

    def decisions(self):
        """The bounded decision log (newest last) — every tick's verdict
        with its evidence, for tests and benches."""
        return list(self._decisions)

    # -- one control iteration (manual drive) ----------------------------
    def tick(self):
        """One observe→decide→act iteration. Returns ``'grow'``,
        ``'shrink'``, ``'cooldown'`` or ``None`` (held steady)."""
        with self._act_lock:
            obs = self.observe()
            self._tick += 1
            pressured = (obs['burn'] >= self.burn_high or
                         obs['page_sheds'] >= max(1, self.shed_high))
            calm = (obs['burn'] <= self.burn_low and
                    obs['page_sheds'] == 0)
            self._history.append((pressured, calm))
            if _obs.enabled():
                _obs.gauge('fleet.autoscale.pressure').set(
                    round(obs['burn'], 4))
            if self._cooldown > 0:
                self._cooldown -= 1
                self._decisions.append(
                    {'tick': self._tick, 'action': 'cooldown',
                     'remaining': self._cooldown, **obs})
                return 'cooldown'
            n = len(self.router.replicas())
            sustained = len(self._history) == self.sustain_ticks
            action = None
            self._last_detail = None
            if sustained and all(p for p, _ in self._history) \
                    and n < self.max_replicas:
                action = self._grow(obs, n)
            elif sustained and all(c for _, c in self._history) \
                    and n > self.min_replicas:
                action = self._shrink(obs, n)
            self._decisions.append(
                {'tick': self._tick, 'action': action or 'steady',
                 'replicas': len(self.router.replicas()), **obs,
                 **(self._last_detail or {})})
            return action

    def _post_action(self):
        """Every action arms the cooldown and resets the observation
        window: the next action needs ``sustain_ticks`` FRESH consecutive
        observations of the post-action fleet, not the window that
        justified this one."""
        self._cooldown = self.cooldown_ticks
        self._history.clear()
        sm = _obs.timeseries.active_sampler()
        if sm is not None:
            # stamp the transition into the PR 18 ring so the replica-
            # count step lands on the timeline at the crossing, not at
            # the next scheduled sample
            sm.sample_now()

    def _grow(self, obs, n):
        existing = {h.name for h in self.router.replicas()}
        name = f'{self.name_prefix}{next(self._names)}'
        while name in existing:
            name = f'{self.name_prefix}{next(self._names)}'
        sw = Stopwatch()
        # build + warm against the persistent compile tier: scale-up with
        # a populated artifact_dir deserializes its whole program set —
        # zero-compile elasticity (per-model artifact_dir= bindings still
        # win inside engine.warmup)
        from .. import compilecache as _cc
        with _cc.use(self.artifact_dir):
            engine = self.replica_factory(name)
            if self.warmup and hasattr(engine, 'warmup'):
                engine.warmup()
        h = self.router.add_replica(name, engine)
        # the half-open gate is the rejoin contract for ANY cold replica,
        # scale-up included: bounded probes first, full rotation after
        h.breaker.force_half_open(reason='scale_up')
        ms = sw.elapsed_ms()
        if _obs.enabled():
            _obs.counter('fleet.autoscale.grows').inc()
            _obs.histogram('fleet.autoscale.scale_up_ms').observe(ms)
            _obs.gauge('fleet.autoscale.replicas').set(n + 1)
            _obs.event('fleet.autoscale', action='grow', replica=name,
                       replicas=n + 1, burn=round(obs['burn'], 4),
                       page_sheds=obs['page_sheds'], ms=round(ms, 3),
                       cooldown_ticks=self.cooldown_ticks, tick=self._tick)
        _obs.flight.record('fleet.autoscale', action='grow', replica=name,
                           replicas=n + 1, burn=round(obs['burn'], 4))
        self._last_detail = {'replica': name, 'ms': round(ms, 3)}
        self._post_action()
        return 'grow'

    def _shrink(self, obs, n):
        victim = self._least_loaded()
        if victim is None:
            return None
        sw = Stopwatch()
        try:
            engine = self.router.drain(victim,
                                       timeout=self.drain_timeout_s)
        except Exception as e:
            # a drain that times out / dies mid-drain leaves the replica
            # out of rotation but NOT removed — the supervisor (or the
            # next shrink attempt after cooldown) deals with the corpse
            if _obs.enabled():
                _obs.counter('fleet.autoscale.shrink_failed').inc()
                _obs.event('fleet.autoscale', action='shrink_failed',
                           replica=victim, error=repr(e), tick=self._tick)
            _obs.flight.record('fleet.autoscale', action='shrink_failed',
                               replica=victim, error=repr(e))
            self._last_detail = {'replica': victim, 'error': repr(e)}
            self._post_action()
            return None
        # the drain contract: nothing in flight survives un-answered
        aborted = engine.queued_count() + engine.resident_count()
        self.router.remove_replica(victim)
        try:
            engine.stop(timeout=self.drain_timeout_s)
        except Exception:
            pass                       # already drained; a stuck worker
        ms = sw.elapsed_ms()           # joins on its own or not at all
        if _obs.enabled():
            _obs.counter('fleet.autoscale.shrinks').inc()
            _obs.histogram('fleet.autoscale.scale_down_ms').observe(ms)
            _obs.gauge('fleet.autoscale.replicas').set(n - 1)
            _obs.event('fleet.autoscale', action='shrink', replica=victim,
                       replicas=n - 1, burn=round(obs['burn'], 4),
                       aborted=aborted, ms=round(ms, 3),
                       cooldown_ticks=self.cooldown_ticks, tick=self._tick)
        _obs.flight.record('fleet.autoscale', action='shrink',
                           replica=victim, replicas=n - 1, aborted=aborted)
        self._last_detail = {'replica': victim, 'aborted': aborted,
                             'ms': round(ms, 3)}
        self._post_action()
        return 'shrink'

    def _least_loaded(self):
        """The shrink victim: least queued+resident among replicas that
        are actually in rotation (not draining, dispatchable)."""
        cands = [h for h in self.router.replicas()
                 if not h.draining and h.engine.dispatchable()]
        if len(cands) <= self.min_replicas:
            return None
        return min(cands, key=lambda h: (h.engine.queued_count()
                                         + h.engine.resident_count(),
                                         h.name)).name

    # -- background mode ------------------------------------------------
    def start(self):
        """Start the background control loop (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name='paddle-tpu-fleet-autoscaler',
            daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            from ..resilience.watchdog import join_thread
            join_thread(t, timeout=timeout)
        self._thread = None

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:
                # the control loop must outlive a bad iteration (factory
                # raising, a race with the supervisor) — but never silently
                if _obs.enabled():
                    _obs.counter('fleet.autoscale.errors').inc()
                    _obs.event('fleet.autoscale', action='error',
                               error=repr(e))
                _obs.flight.record('fleet.autoscale', action='error',
                                   error=repr(e))
            self._stop.wait(self.check_interval_s)
