"""Fixed bucket shapes: the retrace firewall of the serving runtime.

A jitted predict path (or an Executor program-cache entry) is compiled per
input *shape signature*. Serving traffic has arbitrary batch sizes and
prompt lengths, so feeding raw request shapes into the compiled path means
one XLA compile per distinct shape — the retrace storm graftlint GL005/GL006
(and now GL013) police statically. The fix is a **closed shape set**: every
batch is padded up to the nearest of a small, fixed list of bucket sizes, so
after one warmup pass over the buckets, steady-state traffic compiles
nothing (``jax.compiles`` stays flat — the bench asserts this).

Helpers here are pure shape math + numpy padding; they run on the host
before anything reaches the compiled callable.
"""
import numpy as np

__all__ = ['DEFAULT_BATCH_BUCKETS', 'BucketSpec', 'select_bucket',
           'pad_to_bucket', 'stack_examples']

# Powers of two up to 16: small enough that warmup is cheap, dense enough
# that padding waste is bounded by 2x at every load level.
DEFAULT_BATCH_BUCKETS = (1, 2, 4, 8, 16)


def select_bucket(n, buckets):
    """Smallest bucket >= ``n``. Raises ValueError when ``n`` exceeds the
    largest bucket (callers split such batches, they never grow a bucket —
    a grown bucket is a fresh compile in the hot path)."""
    if n <= 0:
        raise ValueError(f"select_bucket: need a positive size, got {n}")
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(
        f"select_bucket: size {n} exceeds the largest bucket "
        f"{max(buckets)} — split the batch or configure larger buckets")


def pad_to_bucket(arr, bucket, axis=0, fill=0):
    """Pad ``arr`` with ``fill`` along ``axis`` up to length ``bucket``.

    The inverse is a plain slice (``out[:n]``); callers keep the real
    length themselves. Never truncates — a too-long input is a caller bug.
    """
    arr = np.asarray(arr)
    n = arr.shape[axis]
    if n > bucket:
        raise ValueError(
            f"pad_to_bucket: length {n} exceeds bucket {bucket} on "
            f"axis {axis}")
    if n == bucket:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, bucket - n)
    return np.pad(arr, widths, mode='constant', constant_values=fill)


def stack_examples(examples, bucket, fill=0):
    """Stack per-request example arrays into one ``[bucket, ...]`` batch.

    ``examples`` is a non-empty list of same-shape arrays (one request
    each); rows beyond ``len(examples)`` are ``fill``-padding. Shape
    mismatches raise — the closed shape set is enforced at admission, not
    discovered as a recompile later.
    """
    first = np.asarray(examples[0])
    for i, e in enumerate(examples[1:], 1):
        e = np.asarray(e)
        if e.shape != first.shape or e.dtype != first.dtype:
            raise ValueError(
                f"stack_examples: example {i} has shape/dtype "
                f"{e.shape}/{e.dtype}, expected {first.shape}/{first.dtype}"
                " — serving inputs must match the registered example spec")
    batch = np.stack([np.asarray(e) for e in examples], axis=0)
    return pad_to_bucket(batch, bucket, axis=0, fill=fill)


class BucketSpec:
    """The closed shape set of one served model.

    - ``batch_buckets``: allowed padded batch sizes (sorted ascending).
    - ``length_buckets``: optional allowed padded lengths for the leading
      (sequence) axis of variable-length inputs — e.g. prompt-length
      buckets for the generative prefill path. ``None`` means inputs are
      fixed-shape and only the batch axis is padded.
    """

    def __init__(self, batch_buckets=DEFAULT_BATCH_BUCKETS,
                 length_buckets=None):
        if not batch_buckets:
            raise ValueError("BucketSpec: batch_buckets must be non-empty")
        self.batch_buckets = tuple(sorted(set(int(b) for b in batch_buckets)))
        if any(b <= 0 for b in self.batch_buckets):
            raise ValueError("BucketSpec: batch buckets must be positive")
        self.length_buckets = None
        if length_buckets is not None:
            self.length_buckets = tuple(
                sorted(set(int(b) for b in length_buckets)))
            if any(b <= 0 for b in self.length_buckets):
                raise ValueError("BucketSpec: length buckets must be positive")

    @property
    def max_batch(self):
        return self.batch_buckets[-1]

    def batch_bucket(self, n):
        return select_bucket(n, self.batch_buckets)

    def length_bucket(self, n):
        if self.length_buckets is None:
            raise ValueError("BucketSpec: no length buckets configured")
        return select_bucket(n, self.length_buckets)

    def __repr__(self):
        return (f"BucketSpec(batch={list(self.batch_buckets)}, "
                f"length={list(self.length_buckets) if self.length_buckets else None})")
