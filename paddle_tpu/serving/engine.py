"""ServingEngine: multi-tenant inference on the warm program cache.

One engine serves many models. Each registered model gets a bounded
admission queue and a runner (``runners.py``); a single worker thread
round-robins the runners, so every pump is one bounded unit of work per
model — a flood on one tenant cannot starve another of scheduler
iterations (it can only fill its own queue and shed).

Registration adapters (all funnel into the two runner shapes):

- ``predict_fn=`` — a batched jnp callable, jit-wrapped here;
- ``layer=`` — an ``nn.Layer`` (e.g. ``jit.load``'s TranslatedLayer after
  re-save, or any eager model): wrapped in no-grad eval calls and
  jit-compiled; ``quantize='int8'`` first routes it through the ``slim``
  per-channel post-training quantization pass (``calib_data`` required);
- ``program=`` — a ``(program, feed_names, fetch_vars)`` triple from
  ``static.io.load_inference_model`` plus an Executor: batches run through
  ``Executor.run``, so the **Executor program cache** is the warm-program
  store (hits/misses already counted on the telemetry spine);
- ``predictor=`` — an ``inference.Predictor`` (portable export);
- ``generative=`` — a ``kv_cache.GenerativeSpec`` for continuous-batching
  decode over the **paged KV cache** (block tables + free-list allocator,
  prefix sharing, chunked prefill, speculative decoding via ``draft=``;
  ``kv_cache='slot'`` retains the PR-6 fixed-slot baseline).

Drive it either with ``start()`` (background worker thread; clients block
on ``Endpoint.predict``) or synchronously with ``pump()`` /
``run_until_idle()`` for deterministic tests and benches.
"""
import threading

import numpy as np

from .. import observability as _obs
from ..resilience.watchdog import join_thread
from .admission import WeightedFairQueue, record_shed
from .paged_runner import PagedGenerativeRunner
from .runners import BatchRunner, GenerativeRunner, _count
from .scheduler import (AdmissionQueue, PendingRequest, QueueFullError,
                        Request)

__all__ = ['ServingEngine', 'Endpoint', 'EngineDeadError']


class EngineDeadError(RuntimeError):
    """Submit/cancel on an engine that was ``kill()``-ed (or never
    started). Distinguishable from model errors so a router can classify
    it as replica death (fail over) rather than request failure."""

# Idle backstop only: submit() and stop() notify the condition, so the
# worker wakes immediately on new work — a long tick avoids 100 Hz busy
# polling in an idle daemon while still bounding any missed wakeup.
_IDLE_TICK = 0.5


class Endpoint:
    """Client-facing handle for one served model."""

    def __init__(self, engine, model):
        self._engine = engine
        self.model = model

    def submit(self, inputs, deadline_ms=None, max_new_tokens=None,
               tenant=None):
        """Enqueue one request -> ``PendingRequest``. Raises
        ``QueueFullError`` when the admission queue sheds it (429-style,
        including the tenant-quota flavor ``QuotaExceededError``),
        ``ValueError`` when inputs don't match the registered spec."""
        return self._engine.submit(self.model, inputs,
                                   deadline_ms=deadline_ms,
                                   max_new_tokens=max_new_tokens,
                                   tenant=tenant)

    def predict(self, inputs, deadline_ms=None, max_new_tokens=None,
                timeout=None, tenant=None):
        """Blocking one-call convenience: submit + result."""
        return self.submit(inputs, deadline_ms=deadline_ms,
                           max_new_tokens=max_new_tokens,
                           tenant=tenant).result(timeout=timeout)


class ServingEngine:
    def __init__(self, queue_capacity=256, default_deadline_ms=None,
                 tenants=None):
        """``tenants=`` attaches a ``serving.admission.TenantArbiter``:
        every model's queue becomes a ``WeightedFairQueue`` (deficit-
        round-robin pop order by tenant weight) and ``submit`` charges the
        tenant's token-bucket quota before the queue push — over-quota
        submits shed as ``QuotaExceededError`` (reason ``'quota'``)
        without ever touching the queue (docs/SERVING.md, "Tenancy +
        autoscaling")."""
        self.queue_capacity = int(queue_capacity)
        self.default_deadline_ms = default_deadline_ms
        self.tenants = tenants         # TenantArbiter or None
        self._models = {}              # name -> runner
        self._queues = {}              # name -> AdmissionQueue
        self._rr = []                  # round-robin order
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._thread = None
        self._stop = threading.Event()
        self._shed = 0
        self._shed_queue_full = 0      # real overload: offered > drained
        self._shed_page_exhaustion = 0  # memory pressure wearing a queue-
        self._shed_quota = 0           # full mask (doctor tells them apart)
        self._submitted = 0
        self._endpoint = None          # MetricsServer this engine owns
        self._own_sampler = False      # ring sampler this engine started
        self._killed = False           # chaos: abrupt death, see kill()

    # -- registration ---------------------------------------------------
    def register(self, name, predict_fn=None, layer=None, program=None,
                 executor=None, predictor=None, generative=None,
                 example=None, bucket_spec=None, quantize=None,
                 calib_data=None, default_max_new_tokens=32,
                 queue_capacity=None, jit_compile=True,
                 kv_cache='paged', page_size=16, num_pages=None,
                 max_concurrency=None, draft=None, draft_k=4,
                 prefix_cache=True, slo_ms=None, slo_objective=0.99,
                 artifact_dir=None):
        """Register one model under ``name``. Exactly one of
        ``predict_fn``/``layer``/``program``/``predictor``/``generative``
        must be given; one-shot kinds also need ``example`` (one request's
        inputs, no batch axis) to pin the closed shape set.

        Generative models decode over a **paged KV cache** by default
        (``kv_cache='paged'``; docs/SERVING.md "Paged KV cache"):
        ``page_size`` tokens per page, ``num_pages`` total (default:
        worst case — size it below that to realize the memory win),
        ``max_concurrency`` block-table rows (default
        ``spec.max_batch``), ``prefix_cache=`` hash-consed shared-prompt
        pages, and ``draft=``/``draft_k=`` speculative decoding (a small
        ``GenerativeSpec`` proposing ``draft_k`` tokens per verify
        step). ``kv_cache='slot'`` keeps the PR-6 fixed-slot cache (the
        memory baseline).

        ``slo_ms=`` declares this model's latency objective for the SLO
        tracker: ``slo_objective`` (default 0.99) of requests must
        complete OK within ``slo_ms`` end-to-end. Violations burn the
        error budget; the doctor's ``slo_burn`` detector fires when the
        burn rate crosses 1x (docs/OBSERVABILITY.md, "SLO tracking").

        ``artifact_dir=`` binds this model to a persistent compile-cache
        directory (``paddle_tpu.compilecache``): ``warmup()`` deserializes
        the model's AOT-serialized executables from it instead of
        compiling — a replica booted against a populated dir serves its
        first request with ``jax.compiles == 0`` — and a first boot
        populates it for the next one. Applies to every kind (predict_fn/
        layer models through the runner's jits, program= through the
        Executor's persistent tier, predictor= through the export's
        cached call path). Overrides the process-wide
        ``PADDLE_TPU_COMPILE_CACHE`` binding for this model's warmup
        (docs/SERVING.md, "AOT registration")."""
        given = [k for k, v in (('predict_fn', predict_fn), ('layer', layer),
                                ('program', program),
                                ('predictor', predictor),
                                ('generative', generative)) if v is not None]
        if len(given) != 1:
            raise ValueError(
                f"register({name!r}): give exactly one model kind, got "
                f"{given or 'none'}")
        if name in self._models:
            raise ValueError(f"register: model {name!r} already registered")
        if quantize is not None and layer is None:
            raise ValueError(
                f"register({name!r}): quantize= applies only to layer= "
                "models (slim PTQ rewrites the Layer); quantize the model "
                "before export for the other kinds")
        if generative is not None:
            bad = [k for k, v in (('example', example),
                                  ('bucket_spec', bucket_spec),
                                  ('calib_data', calib_data)) if v is not None]
            if bad:
                raise ValueError(
                    f"register({name!r}): {bad} do not apply to "
                    "generative= models — prompt buckets and batch size "
                    "come from the GenerativeSpec itself")
            if kv_cache not in ('paged', 'slot'):
                raise ValueError(
                    f"register({name!r}): kv_cache must be 'paged' or "
                    f"'slot', got {kv_cache!r}")
            if kv_cache == 'slot':
                paged_only = [k for k, v in (
                    ('num_pages', num_pages), ('draft', draft),
                    ('max_concurrency', max_concurrency)) if v is not None]
                if paged_only:
                    raise ValueError(
                        f"register({name!r}): {paged_only} need the paged "
                        "KV cache — drop kv_cache='slot' (paged is the "
                        "default) to use pages, prefix sharing, and "
                        "speculative decoding")
        else:
            paged_given = [k for k, v in (
                ('num_pages', num_pages), ('draft', draft),
                ('max_concurrency', max_concurrency)) if v is not None]
            if paged_given:
                raise ValueError(
                    f"register({name!r}): {paged_given} apply only to "
                    "generative= models (the paged KV cache)")
        if queue_capacity is not None and int(queue_capacity) < 1:
            raise ValueError(
                f"register({name!r}): queue_capacity must be >= 1, got "
                f"{queue_capacity!r}")
        if slo_ms is not None:
            from ..observability import slo as _slo
            _slo.set_objective(name, slo_ms, slo_objective)
        capacity = (self.queue_capacity if queue_capacity is None
                    else queue_capacity)
        if self.tenants is not None:
            queue = WeightedFairQueue(name, capacity, arbiter=self.tenants)
        else:
            queue = AdmissionQueue(name, capacity)
        if generative is not None:
            if kv_cache == 'paged':
                runner = PagedGenerativeRunner(
                    name, queue, generative, page_size=page_size,
                    num_pages=num_pages, max_concurrency=max_concurrency,
                    draft=draft, draft_k=draft_k, prefix_cache=prefix_cache,
                    default_max_new_tokens=default_max_new_tokens)
            else:
                runner = GenerativeRunner(
                    name, queue, generative,
                    default_max_new_tokens=default_max_new_tokens)
        else:
            if example is None:
                raise ValueError(
                    f"register({name!r}): one-shot models need example= "
                    "(one request's inputs, no batch axis) to fix the "
                    "compiled shape set")
            if predict_fn is not None:
                # jit_compile=False is for callables that are already
                # compiled (or host-side wrappers, e.g. faultinject
                # slow_model around a jitted fn)
                fn = predict_fn
            elif layer is not None:
                fn = self._layer_fn(name, layer, quantize, calib_data,
                                    example)
            elif predictor is not None:
                fn = self._predictor_fn(predictor)
                jit_compile = False    # export manages its own compilation
            else:
                fn = self._program_fn(name, program, executor)
                jit_compile = False    # Executor program cache owns it
            runner = BatchRunner(name, queue, fn, example,
                                 bucket_spec=bucket_spec,
                                 jit_compile=jit_compile)
        runner.artifact_dir = artifact_dir
        with self._cond:
            self._models[name] = runner
            self._queues[name] = queue
            self._rr.append(name)
        if _obs.enabled():
            _obs.gauge('serving.models').set(len(self._models))
        return Endpoint(self, name)

    def _layer_fn(self, name, layer, quantize, calib_data, example):
        import inspect
        from ..core.tensor import Tensor
        from ..core import autograd
        if quantize is not None:
            if quantize != 'int8':
                raise ValueError(
                    f"register({name!r}): quantize must be 'int8', "
                    f"got {quantize!r}")
            if calib_data is None:
                raise ValueError(
                    f"register({name!r}): quantize='int8' needs "
                    "calib_data= (iterable of input batches for the slim "
                    "PTQ calibration pass)")
            from ..slim import PostTrainingQuantization
            layer = PostTrainingQuantization(layer, calib_data).quantize()
        layer.eval()
        # Bind feeds to forward's parameters BY NAME: a dict has no
        # positional order, so multi-input layers whose feed names don't
        # match forward's parameter names must be registered through
        # predict_fn= (where the caller owns the binding) rather than be
        # silently miswired by an arbitrary key sort.
        if len(example) == 1:
            order = list(example)
        else:
            try:
                params = [
                    p.name for p in
                    inspect.signature(layer.forward).parameters.values()
                    if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
            except (TypeError, ValueError):
                params = []
            if not set(example) <= set(params):
                raise ValueError(
                    f"register({name!r}): multi-input layer — feed names "
                    f"{sorted(example)} must match {type(layer).__name__}"
                    f".forward parameter names {params} so arguments bind "
                    "unambiguously; rename the feeds or register via "
                    "predict_fn= with explicit binding")
            order = [p for p in params if p in example]

        def fn(feeds):
            vals = [Tensor(feeds[k]) for k in order]
            with autograd.no_grad():
                out = layer(*vals)
            if isinstance(out, (tuple, list)):
                return type(out)(o._value if isinstance(o, Tensor) else o
                                 for o in out)
            return out._value if isinstance(out, Tensor) else out
        return fn

    def _predictor_fn(self, predictor):
        def fn(feeds):
            outs = predictor.run({k: np.asarray(v)
                                  for k, v in feeds.items()})
            return tuple(outs)
        return fn

    def _program_fn(self, name, program, executor):
        if executor is None:
            raise ValueError(
                f"register({name!r}): program= also needs executor=")
        try:
            prog, feed_names, fetch_vars = program
        except (TypeError, ValueError):
            raise ValueError(
                f"register({name!r}): program= expects the (program, "
                "feed_names, fetch_vars) triple load_inference_model "
                "returns") from None

        def fn(feeds):
            outs = executor.run(prog,
                                feed={k: np.asarray(v)
                                      for k, v in feeds.items()},
                                fetch_list=list(fetch_vars))
            return tuple(outs)
        return fn

    # -- client surface -------------------------------------------------
    def endpoint(self, name):
        if name not in self._models:
            raise KeyError(f"serving: no model {name!r} registered "
                           f"(have {sorted(self._models)})")
        return Endpoint(self, name)

    def has_model(self, name):
        return name in self._models

    def model_kind(self, name):
        """'generative' or 'batch' for a registered model (KeyError else)."""
        return self._models[name].kind

    def page_starved(self, model):
        """Is ``model``'s paged runner currently unable to allocate KV
        pages? Always False for non-paged models — a router health gate,
        mirrored in ``/healthz``."""
        runner = self._models.get(model)
        if runner is None:
            return False
        return bool(getattr(runner, 'page_starved', lambda: False)())

    def submit(self, model, inputs, deadline_ms=None, max_new_tokens=None,
               tenant=None):
        if self._killed:
            raise EngineDeadError(
                f"serving: engine is dead (killed) — request for "
                f"{model!r} refused")
        runner = self._models.get(model)
        if runner is None:
            raise KeyError(f"serving: no model {model!r} registered")
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        if max_new_tokens is not None and int(max_new_tokens) < 1:
            raise ValueError(
                f"serving: max_new_tokens must be >= 1, got "
                f"{max_new_tokens!r}")
        req = Request(model, inputs, deadline_ms=deadline_ms,
                      max_new_tokens=max_new_tokens, tenant=tenant)
        runner.validate(req)
        if self.tenants is not None:
            # quota gate at the front door, BEFORE the queue push: a shed
            # here never touches the queue, so the queue-full path below
            # can keep stamping its own reasons without masking 'quota'
            try:
                self.tenants.check(req.tenant, model)
            except QueueFullError as e:
                self._record_shed(req, e.reason)
                raise
        _count('serving.requests')
        if _obs.enabled():
            # open the request's async trace lane BEFORE the queue push:
            # the worker may pop, run, and emit the closing async_end
            # before this thread resumes — a begin after that would leave
            # Perfetto an unmatched lane. Everything the runners stamp
            # with this id (prefill chunks, decode iterations,
            # speculative verify) renders as ONE connected flow, closed
            # by finish_request's async_end (or the shed edge below).
            _obs.async_begin('request', req.id, cat='serving.request',
                             model=model, deadline_ms=deadline_ms,
                             tenant=req.tenant)
        try:
            self._queues[model].push(req)
        except QueueFullError as e:
            # attribute the shed: a queue that backed up behind a page-
            # starved runner is memory pressure, not traffic overload —
            # the doctor must not prescribe replicas for an OOM
            starved = getattr(runner, 'page_starved', lambda: False)()
            e.reason = 'page_exhaustion' if starved else 'queue_full'
            self._record_shed(req, e.reason, lane_open=True)
            raise
        with self._cond:
            self._submitted += 1
            if _obs.enabled():
                _obs.gauge('serving.queue_depth').set(
                    sum(len(q) for q in self._queues.values()))
            self._cond.notify_all()
        return PendingRequest(req, self.alive)

    def _record_shed(self, req, reason, lane_open=False):
        """Tally one shed (reason: queue_full / page_exhaustion / quota)
        under the lock, mirror to telemetry, attribute to the tenant."""
        with self._lock:
            # submit() runs on arbitrary client threads while the
            # endpoint's health probe reads these; += is a racy
            # read-modify-write without the lock
            self._shed += 1
            if reason == 'page_exhaustion':
                self._shed_page_exhaustion += 1
            elif reason == 'quota':
                self._shed_quota += 1
            else:
                self._shed_queue_full += 1
        _count('serving.shed')
        _count(f'serving.shed.{reason}')
        record_shed(req.tenant, reason)
        if _obs.enabled():
            _obs.event('serving.shed', model=req.model, request=req.id,
                       reason=reason, tenant=req.tenant)
            if lane_open:
                _obs.async_end('request', req.id, cat='serving.request',
                               status='shed', reason=reason)

    def cancel(self, pending):
        """Withdraw a still-queued request: it is removed from the
        admission queue and completed with status ``'cancelled'`` without
        ever running. Returns True on success, False when the worker
        already owns the request (it will run to completion; discard the
        answer). The router's hedge path uses this to reap the losing
        duplicate for free when it never reached a batch slot."""
        req = pending._req if isinstance(pending, PendingRequest) else pending
        queue = self._queues.get(req.model)
        if queue is None or not queue.remove(req):
            return False
        from .scheduler import STATUS_CANCELLED
        req.complete(STATUS_CANCELLED)
        _count('serving.cancelled')
        if _obs.enabled():
            _obs.event('serving.cancelled', model=req.model, request=req.id)
            _obs.async_end('request', req.id, cat='serving.request',
                           status='cancelled')
        return True

    def queued_count(self, model=None):
        """Requests admitted but not yet popped by a runner."""
        with self._lock:
            if model is not None:
                q = self._queues.get(model)
                return 0 if q is None else len(q)
            return sum(len(q) for q in self._queues.values())

    def resident_count(self, model=None):
        """Generative requests currently resident in KV batch slots
        (mid-decode). One-shot batches run synchronously inside a single
        pump, so they are never observed resident between pumps."""
        with self._lock:
            runners = ([self._models[model]] if model in self._models
                       else [] if model is not None
                       else list(self._models.values()))
        return sum(sum(1 for s in r.slots if s is not None)
                   for r in runners if r.kind == 'generative')

    # -- scheduler loop -------------------------------------------------
    def pump(self):
        """One scheduler iteration over every model (round-robin order).
        Returns True when any runner did work."""
        if self._killed:
            return False               # a dead replica does no work
        # snapshot under the lock: register() may grow these dicts from
        # another thread and iterating a resizing dict raises
        with self._lock:
            order = list(self._rr)
            if order:
                self._rr.append(self._rr.pop(0))
            runners = [self._models[n] for n in order]
            queues = list(self._queues.values())
        did = False
        for runner in runners:
            if runner.has_work():
                did = runner.step() or did
        if _obs.enabled():
            _obs.gauge('serving.queue_depth').set(
                sum(len(q) for q in queues))
            _obs.gauge('serving.active_slots').set(sum(
                sum(1 for s in r.slots if s is not None)
                for r in runners if r.kind == 'generative'))
        return did

    def run_until_idle(self, max_steps=100000):
        """Pump until no runner has work (manual-drive mode for tests and
        benches). Returns the number of iterations that did work."""
        steps = 0
        for _ in range(int(max_steps)):
            if not self.pump():
                if not any(r.has_work() for r in self._models.values()):
                    return steps
            else:
                steps += 1
        return steps

    def warmup(self):
        """Ready every registered model's closed shape set now, so the
        first real request never pays an XLA compile. Models registered
        with ``artifact_dir=`` (or a process-wide
        ``PADDLE_TPU_COMPILE_CACHE`` binding) deserialize their
        AOT-serialized executables instead of compiling them — and a
        first boot commits what it compiled for the next one. Returns
        {model: programs_readied}."""
        from .. import compilecache as _cc
        out = {}
        with _obs.timer('serving.warmup'):
            for name, runner in self._models.items():
                with _cc.use(getattr(runner, 'artifact_dir', None)):
                    out[name] = runner.warmup() \
                        if hasattr(runner, 'warmup') else 0
        return out

    def start(self):
        """Start the background worker thread (idempotent). A worker that
        died from an escaped exception (counted as serving.worker_crash)
        is replaced, not silently left dead. With telemetry enabled and
        ``PADDLE_TPU_TELEMETRY_HTTP`` set, the live ``/metrics`` +
        ``/healthz`` endpoint comes up alongside (mission control)."""
        # flight recorder: a serving worker that dies takes its black box
        # with it unless the crash hooks are in (always-on, telemetry or
        # not — threading.excepthook catches an escaped worker exception)
        _obs.flight.install_crash_hooks()
        if _obs.enabled():
            from ..observability import endpoint as _endpoint
            _endpoint.maybe_start_from_env(extra_health=self._health)
            # ring sampler: the doctor's trend detectors (page_leak,
            # latency_creep, qps_collapse) need timelines of this
            # engine's gauges/histograms, not just the last frame
            had = _obs.timeseries.active_sampler() is not None
            self._own_sampler = (_obs.timeseries.start_sampler() is not None
                                 and not had)
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._worker, name='paddle-tpu-serving', daemon=True)
            self._thread.start()
        return self

    def start_endpoint(self, port=0, host=None):
        """Explicitly export this engine's live ``/metrics`` + ``/healthz``
        (``port=0`` picks a free port; binds 127.0.0.1 unless ``host`` or
        ``PADDLE_TPU_TELEMETRY_HTTP_HOST`` widens it). Returns the
        ``observability.MetricsServer``; ``stop()`` tears it down."""
        from ..observability.endpoint import MetricsServer
        if self._endpoint is None:
            self._endpoint = MetricsServer(
                host=host, port=port, extra_health=self._health).start()
        return self._endpoint

    def _health(self):
        """The serving slice of ``/healthz``."""
        with self._lock:
            queues = {n: len(q) for n, q in self._queues.items()}
        starved = {n: bool(getattr(r, 'page_starved', lambda: False)())
                   for n, r in self._models.items()}
        out = {'serving': {
            'worker_alive': self.alive(),
            'models': sorted(queues),
            'queue_depth': queues,
            'resident': self.resident_count(),
            'page_starved': starved,
            'submitted': self._submitted,
            'shed': self._shed,
        }}
        from ..observability import slo as _slo
        burns = _slo.burn_rates()
        if burns:
            out['serving']['slo_burn'] = burns
        return out

    def alive(self):
        if self._killed:
            return False
        return self._thread is not None and self._thread.is_alive()

    @property
    def killed(self):
        return self._killed

    def dispatchable(self):
        """Can this engine accept work and eventually run it? False once
        ``kill()``-ed, or once a started worker thread has died (crash).
        A never-started engine IS dispatchable — manual ``pump()`` mode —
        which is also why this is not ``alive()``: alive() answers "is the
        background worker running", dispatchable() answers "is this
        replica a valid dispatch target"."""
        if self._killed:
            return False
        with self._lock:
            t = self._thread
        return t is None or t.is_alive()

    def kill(self):
        """Chaos surface: die abruptly, the in-process analogue of a
        replica SIGKILL. Unlike ``stop()``, queued and resident requests
        are NOT completed — they are stranded exactly as a real crash
        strands them, so their clients' watchdog-bounded waits fire and a
        router above can observe the loss and re-dispatch. The worker
        thread (if any) exits on its next iteration; ``alive()`` is False
        immediately. Idempotent."""
        if self._killed:
            return
        self._killed = True
        with self._cond:
            self._stop.set()
            self._cond.notify_all()
        _count('serving.killed')
        if _obs.enabled():
            _obs.event('serving.killed',
                       queued=sum(len(q) for q in self._queues.values()))
        _obs.flight.record('serving.killed', models=sorted(self._models))

    def stop(self, timeout=10.0):
        """Stop the worker; queued AND in-flight (KV-slot-resident)
        requests are completed as errors rather than stranded (their
        clients' bounded waits would fire anyway, but a shaped answer —
        with any partial generative output — beats a timeout)."""
        with self._cond:
            self._stop.set()
            self._cond.notify_all()
            t = self._thread
        # Join BEFORE clearing _thread: alive() must stay True while the
        # worker finishes its current batch, or clients blocked in
        # result() race into a spurious "engine stopped" WatchdogTimeout
        # for a request that completes milliseconds later. A join timeout
        # must abort the shutdown — evicting KV slots under a live worker
        # would have two threads mutating runner state.
        if t is not None and not join_thread(t, timeout=timeout):
            from ..resilience.watchdog import WatchdogTimeout
            raise WatchdogTimeout(
                f"serving: worker thread still running {timeout:.1f}s "
                "after stop() — a batch is stuck; not evicting in-flight "
                "requests under a live worker", what='serving worker join',
                waited=timeout)
        with self._cond:
            self._thread = None
        from .runners import finish_request
        from .scheduler import STATUS_ERROR
        for name, runner in self._models.items():
            for req, outputs in runner.evict_in_flight():
                finish_request(
                    req, STATUS_ERROR, outputs,
                    error=RuntimeError(
                        f"serving: engine stopped with request {req.id} "
                        "mid-decode"))
        for name, q in self._queues.items():
            for req in q.drain():
                finish_request(
                    req, STATUS_ERROR,
                    error=RuntimeError(
                        f"serving: engine stopped before request "
                        f"{req.id} ran"))
        if self._endpoint is not None:
            self._endpoint.stop()
            self._endpoint = None
        from ..observability import endpoint as _endpoint
        _endpoint.detach_health(self._health)
        if self._own_sampler:
            sm = _obs.timeseries.active_sampler()
            if sm is not None:
                sm.sample_now()   # the engine's tail lands in the ring
            _obs.timeseries.stop_sampler()
            self._own_sampler = False

    def _worker(self):
        try:
            while not self._stop.is_set():
                did = self.pump()
                if not did:
                    with self._cond:
                        if self._stop.is_set():
                            break
                        has = any(r.has_work()
                                  for r in self._models.values())
                        if not has:
                            self._cond.wait(_IDLE_TICK)
        except BaseException as e:
            # Runners contain model errors, so nothing should escape pump();
            # if something does, leave a trace — a dead worker otherwise
            # looks like an idle engine while every client times out.
            _count('serving.worker_crash')
            if _obs.enabled():
                _obs.event('serving.worker_crash', error=repr(e))
            raise

    # -- introspection --------------------------------------------------
    def stats(self):
        from ..observability import slo as _slo
        out = {
            'submitted': self._submitted,
            'shed': self._shed,
            'shed_queue_full': self._shed_queue_full,
            'shed_page_exhaustion': self._shed_page_exhaustion,
            'shed_quota': self._shed_quota,
            'queue_depth': {n: len(q) for n, q in self._queues.items()},
            'models': {n: r.stats.as_dict()
                       for n, r in self._models.items()},
            'slo_burn': _slo.burn_rates(),
        }
        if self.tenants is not None:
            from .admission import tenant_stats
            out['tenants'] = {'policies': self.tenants.stats(),
                              'ledger': tenant_stats()}
        return out
