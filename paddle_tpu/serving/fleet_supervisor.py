"""FleetSupervisor: relaunch dead serving replicas behind the router.

The serving sibling of ``distributed.launch._Supervisor`` (elastic
training, PR 14): where that one watches rank *processes* and re-forms
the world, this one watches replica *engines* behind a ``FleetRouter``
and restores fleet capacity — same contract, different substrate:

- a replica whose engine died (``kill()``-ed, or its worker thread
  crashed) is detected on the next sweep; the corpse is **reaped**
  (``stop()`` completes its stranded queued/resident requests as shaped
  errors, so clients fail over in one tick instead of waiting out their
  watchdogs);
- a fresh engine from ``replica_factory(name)`` takes its slot, bounded
  by ``max_restarts`` per replica (exhaustion leaves the replica out of
  rotation and emits ``fleet.restarts_exhausted`` — capacity loss is a
  fact, not a retry loop);
- the relaunched replica **rejoins through the router's half-open gate**
  (``router.readmit(..., warm=False)``): its compile warmup meets
  bounded probe traffic, never the full request stream;
- death→rejoin wall time lands on the ``fleet.recovery_ms`` histogram,
  and every transition is a ``fleet.*`` event + flight-recorder entry.

Drive it manually with ``check_once()`` (deterministic tests) or as a
background thread via ``start()``/``stop()``.
"""
import threading

from .. import observability as _obs
from ..observability.timing import Stopwatch
from ..resilience.retry import backoff_delay

__all__ = ['FleetSupervisor']


class FleetSupervisor:
    """Watch a ``FleetRouter``'s replicas; reap + relaunch the dead.

    ``replica_factory(name)`` must return a ready ``ServingEngine`` —
    models registered, and ``start()``-ed if the fleet runs background
    workers (the factory owns that choice; manual-drive fleets return
    un-started engines). ``warmup=True`` pre-compiles the new engine's
    shape set before it rejoins, so even the half-open probes never pay
    an XLA compile. ``artifact_dir=`` removes even those warmup compiles
    from the recovery path: the factory's registration and the warmup run
    against a persistent compile cache (``paddle_tpu.compilecache``), so
    a relaunch against a populated dir deserializes its whole program set
    — death→rejoin without a compile storm (the first launch populates
    the dir for every later one). ``relaunch_backoff_s`` paces repeated
    restarts of the same replica on the shared retry curve (0 keeps chaos
    tests fast)."""

    def __init__(self, router, replica_factory, max_restarts=3,
                 check_interval_s=0.2, warmup=True, relaunch_backoff_s=0.0,
                 reap_timeout_s=5.0, artifact_dir=None):
        self.router = router
        self.replica_factory = replica_factory
        self.artifact_dir = artifact_dir
        self.max_restarts = int(max_restarts)
        self.check_interval_s = float(check_interval_s)
        self.warmup = bool(warmup)
        self.relaunch_backoff_s = float(relaunch_backoff_s)
        self.reap_timeout_s = float(reap_timeout_s)
        # the budget lock makes claim-and-increment atomic: a manual
        # check_once() racing the background sweep must not both observe
        # the same count and double-relaunch one replica
        self._budget_lock = threading.Lock()
        self._restarts = {}            # replica -> relaunch count
        self._exhausted = set()        # emitted fleet.restarts_exhausted
        self._thread = None
        self._stop = threading.Event()

    # -- one sweep (manual drive) ---------------------------------------
    def check_once(self):
        """One supervision sweep over the fleet. Returns the list of
        replica names relaunched this sweep."""
        relaunched = []
        for h in self.router.replicas():
            if h.draining or h.engine.dispatchable():
                continue
            name = h.name
            with self._budget_lock:
                used = self._restarts.get(name, 0)
                exhausted = used >= self.max_restarts
                first_exhaustion = exhausted and name not in self._exhausted
                if first_exhaustion:
                    self._exhausted.add(name)
                if not exhausted:
                    # claim the relaunch slot before doing the (slow,
                    # unlocked) reap+rebuild so no concurrent sweep
                    # relaunches the same replica on the same budget
                    self._restarts[name] = used + 1
            if exhausted:
                if first_exhaustion:
                    if _obs.enabled():
                        _obs.counter('fleet.restarts_exhausted').inc()
                        _obs.event('fleet.restarts_exhausted', replica=name,
                                   restarts=used)
                    _obs.flight.record('fleet.restarts_exhausted',
                                       replica=name, restarts=used)
                continue
            sw = Stopwatch()
            self._reap(h)
            if self.relaunch_backoff_s:
                self._stop.wait(backoff_delay(
                    used + 1, backoff=self.relaunch_backoff_s, jitter=0.0))
            if _obs.enabled():
                _obs.counter('fleet.relaunches').inc()
                _obs.event('fleet.replica_relaunch', replica=name,
                           attempt=used + 1)
            _obs.flight.record('fleet.replica_relaunch', replica=name,
                               attempt=used + 1)
            # rebuild + warm against the persistent compile tier: with a
            # populated artifact_dir the relaunch deserializes instead of
            # recompiling (per-model artifact_dir= bindings still win
            # inside engine.warmup)
            from .. import compilecache as _cc
            with _cc.use(self.artifact_dir):
                engine = self.replica_factory(name)
                if self.warmup and hasattr(engine, 'warmup'):
                    engine.warmup()
            self.router.readmit(name, engine=engine, warm=False)
            recovery_ms = sw.elapsed_ms()
            if _obs.enabled():
                _obs.histogram('fleet.recovery_ms').observe(recovery_ms)
                _obs.event('fleet.replica_rejoin', replica=name,
                           restarts=used + 1,
                           recovery_ms=round(recovery_ms, 3))
            _obs.flight.record('fleet.replica_rejoin', replica=name,
                               recovery_ms=round(recovery_ms, 3))
            relaunched.append(name)
        return relaunched

    def _reap(self, handle):
        """Complete the corpse's stranded requests as shaped errors —
        ``stop()`` on a killed engine drains queues and evicts residents,
        turning every client's would-be watchdog timeout into an
        immediate, classifiable replica fault."""
        try:
            handle.engine.stop(timeout=self.reap_timeout_s)
        except Exception as e:
            # a corpse that will not even join its worker: clients fall
            # back to their bounded waits; record it and move on
            if _obs.enabled():
                _obs.event('fleet.reap_failed', replica=handle.name,
                           error=repr(e))
            _obs.flight.record('fleet.reap_failed', replica=handle.name,
                               error=repr(e))

    def restarts(self):
        """{replica: relaunch count} so far."""
        with self._budget_lock:
            return dict(self._restarts)

    # -- background mode ------------------------------------------------
    def start(self):
        """Start the background sweep thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name='paddle-tpu-fleet-supervisor',
            daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout=5.0):
        self._stop.set()
        t = self._thread
        if t is not None:
            from ..resilience.watchdog import join_thread
            join_thread(t, timeout=timeout)
        self._thread = None

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.check_once()
            except Exception as e:
                # supervision must outlive a bad sweep (a replica factory
                # raising, a race with drain) — but never silently
                if _obs.enabled():
                    _obs.counter('fleet.supervisor_errors').inc()
                    _obs.event('fleet.supervisor_error', error=repr(e))
                _obs.flight.record('fleet.supervisor_error', error=repr(e))
            self._stop.wait(self.check_interval_s)
