"""Slot-based KV cache for iteration-level (continuous) batch decoding.

The decode hot path of a text model is one token per step per sequence;
recomputing attention over the whole prefix each step is O(S^2) per token.
The KV cache stores every layer's keys/values at fixed ``[max_batch,
max_seq]`` slots so one decode step is O(S) — and, crucially for the
serving engine, the cache shapes are **static**: requests join by writing
their prefill K/V into a free slot and leave by freeing it, while the
jitted decode step always runs at ``[max_batch]``. No shape ever changes,
so nothing ever recompiles (the Orca/vLLM iteration-level scheduling
idea, restricted to fixed slots). The fixed-slot layout is the MEMORY
BASELINE: every sequence pays ``max_seq`` rows; ``paged_kv.py`` replaces
the slots with block-table pages (the serving default) and this module's
``GenerativeSpec`` carries both contracts.

Everything here is pure ``jnp`` — safe inside ``jax.jit``; the cache is a
plain dict pytree threaded through the jitted prefill/decode calls.

``TinyCausalLM`` is the reference ``GenerativeSpec`` implementation (one
pre-LN attention block + tied output head): small enough to read in one
sitting, real enough that tests verify cached decode against a full
no-cache forward, token for token.
"""
import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['create_cache', 'write_prompt', 'write_token', 'attend',
           'attend_prompt', 'GenerativeSpec', 'TinyCausalLM']


def create_cache(num_layers, max_batch, max_seq, num_heads, head_dim,
                 dtype=jnp.float32):
    """Zeroed cache pytree: ``{'k','v'}`` of ``[L, B, S, H, D]``."""
    shape = (int(num_layers), int(max_batch), int(max_seq),
             int(num_heads), int(head_dim))
    # host-built zeros: device transfer only, no tiny fill-program compile
    # (keeps an AOT cold boot at jax.compiles == 0 — see compilecache)
    z = np.zeros(shape, np.dtype(dtype))
    return {'k': jnp.asarray(z), 'v': jnp.asarray(z)}


def write_prompt(cache, layer, slot, k, v):
    """Write one sequence's prefill K/V (``[Lp, H, D]``) into ``slot`` at
    positions ``0..Lp-1``. ``Lp`` is the (static) prompt bucket length;
    rows beyond the real length hold padding garbage that ``attend`` masks
    out by position. ``slot`` may be a traced scalar — joining a different
    slot is not a recompile."""
    k = jnp.asarray(k)[None]           # [1, Lp, H, D]
    v = jnp.asarray(v)[None]
    start = (layer, slot, 0, 0, 0)
    return {
        'k': jax.lax.dynamic_update_slice(cache['k'], k[None], start),
        'v': jax.lax.dynamic_update_slice(cache['v'], v[None], start),
    }


def write_token(cache, layer, k, v, positions):
    """Write one decode step's K/V (``[B, H, D]``) at per-slot
    ``positions`` (``[B]`` int). Inactive slots write at position 0 —
    harmless garbage that the next prefill into that slot overwrites."""
    b = jnp.arange(cache['k'].shape[1])
    return {
        'k': cache['k'].at[layer, b, positions].set(k),
        'v': cache['v'].at[layer, b, positions].set(v),
    }


def attend(cache, layer, q, lengths):
    """Masked attention read over the cache: ``q`` ``[B, H, D]``,
    ``lengths`` ``[B]`` = number of valid positions per slot (the current
    token's K/V already written). Returns ``[B, H, D]``."""
    k = cache['k'][layer]              # [B, S, H, D]
    v = cache['v'][layer]
    d = q.shape[-1]
    scores = jnp.einsum('bhd,bshd->bhs', q, k) / jnp.sqrt(float(d))
    mask = jnp.arange(k.shape[1])[None, None, :] < lengths[:, None, None]
    scores = jnp.where(mask, scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum('bhs,bshd->bhd', w, v)


def attend_prompt(q, k, v):
    """Causal self-attention within one prompt (prefill): ``[Lp, H, D]``
    each. Padded rows beyond the real length produce garbage outputs the
    caller never reads (only the last *real* row's logits matter)."""
    d = q.shape[-1]
    lp = q.shape[0]
    scores = jnp.einsum('ihd,jhd->hij', q, k) / jnp.sqrt(float(d))
    causal = jnp.tril(jnp.ones((lp, lp), bool))[None]
    scores = jnp.where(causal, scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum('hij,jhd->ihd', w, v)


class GenerativeSpec:
    """What a model must provide to decode under continuous batching.

    Subclasses implement three pure functions (all jitted by the runner,
    so bodies must be trace-safe — no Python branching on traced values):

    - ``init_cache() -> pytree`` of ``[.., max_batch, max_seq, ..]`` arrays
    - ``prefill(cache, tokens[Lp], length, slot) -> (cache, logits[V])``
      — process one padded prompt into ``slot``, return the next-token
      logits at the last real position. ``length``/``slot`` are traced
      scalars; ``Lp`` is one of ``prompt_buckets`` (static).
    - ``decode(cache, tokens[B], positions[B]) -> (cache, logits[B, V])``
      — one token step for every slot at once, ``B == max_batch`` fixed.

    **Paged contract** (the default serving path — ``paged_kv.py`` has the
    primitives, ``paged_runner.py`` the scheduler): four more pure
    functions over a paged cache + block tables instead of slots. The
    slot contract above is retained as the memory-baseline comparison
    (``register(..., kv_cache='slot')``).

    - ``init_paged_cache(num_pages, page_size) -> pytree`` of
      ``[.., P, page_size, ..]`` arrays
    - ``prefill_chunk(cache, block_row[MP], tokens[Cb], start, length)
      -> (cache, logits[Cb, V])`` — one chunk of one sequence's prompt
      at absolute offset ``start`` (chunked prefill / prefix-cache
      resume); rows at or beyond ``length`` are bucket padding.
    - ``decode_paged(cache, block_tables[B, MP], tokens[B],
      positions[B]) -> (cache, logits[B, V])`` — one token per row.
    - ``verify_tokens(cache, block_tables[B, MP], tokens[B, K],
      positions[B, K]) -> (cache, logits[B, K, V])`` — process ``K``
      tokens per row in ONE step (the speculative-decoding verify;
      ``decode_paged`` is its ``K=1`` special case).
    """

    max_batch = 1
    max_seq = 128
    eos_id = None                      # None: stop only on max_new_tokens
    prompt_buckets = (16, 32, 64)

    def init_cache(self):
        raise NotImplementedError

    def prefill(self, cache, tokens, length, slot):
        raise NotImplementedError

    def decode(self, cache, tokens, positions):
        raise NotImplementedError

    # -- paged contract (kv_cache='paged', the default) -----------------
    def init_paged_cache(self, num_pages, page_size):
        raise NotImplementedError

    def prefill_chunk(self, cache, block_row, tokens, start, length):
        raise NotImplementedError

    def decode_paged(self, cache, block_tables, tokens, positions):
        cache, logits = self.verify_tokens(
            cache, block_tables, tokens[:, None], positions[:, None])
        return cache, logits[:, 0]

    def verify_tokens(self, cache, block_tables, tokens, positions):
        raise NotImplementedError


class TinyCausalLM(GenerativeSpec):
    """Reference spec: embed + learned positions, one pre-LN causal
    attention block with residual, tied vocab head.

    ``params`` maps ``emb [V,E]``, ``pos [max_seq,E]``, ``wq/wk/wv/wo
    [E,E]``; the output head reuses ``emb`` transposed. Deterministic
    (greedy decode happens in the runner); everything trace-safe.
    """

    def __init__(self, params, num_heads, max_batch=4, max_seq=128,
                 eos_id=None, prompt_buckets=(8, 16, 32)):
        self.p = {k: jnp.asarray(v) for k, v in params.items()}
        vocab, embed = self.p['emb'].shape
        if embed % num_heads:
            raise ValueError("embed dim must divide num_heads")
        self.num_heads = int(num_heads)
        self.head_dim = embed // num_heads
        self.vocab = vocab
        self.max_batch = int(max_batch)
        self.max_seq = int(max_seq)
        self.eos_id = eos_id
        self.prompt_buckets = tuple(sorted(prompt_buckets))

    @classmethod
    def random(cls, vocab=64, embed=32, num_heads=4, max_seq=64, seed=0,
               **kw):
        """Small random instance for tests/benches (numpy RNG, host-side)."""
        r = np.random.RandomState(seed)

        def w(*s):
            return (r.randn(*s) * 0.1).astype(np.float32)
        params = {'emb': w(vocab, embed), 'pos': w(max_seq, embed),
                  'wq': w(embed, embed), 'wk': w(embed, embed),
                  'wv': w(embed, embed), 'wo': w(embed, embed)}
        return cls(params, num_heads, max_seq=max_seq, **kw)

    # -- shared block ---------------------------------------------------
    def _norm(self, x):
        m = jnp.mean(x, axis=-1, keepdims=True)
        v = jnp.var(x, axis=-1, keepdims=True)
        return (x - m) / jnp.sqrt(v + 1e-5)

    def _qkv(self, x):
        h, d = self.num_heads, self.head_dim
        n = self._norm(x)

        def split(w):
            y = n @ w
            return y.reshape(y.shape[:-1] + (h, d))
        return split(self.p['wq']), split(self.p['wk']), split(self.p['wv'])

    def _head(self, y):
        return y @ self.p['emb'].T

    def init_cache(self):
        return create_cache(1, self.max_batch, self.max_seq,
                            self.num_heads, self.head_dim)

    def prefill(self, cache, tokens, length, slot):
        lp = tokens.shape[0]
        x = self.p['emb'][tokens] + self.p['pos'][:lp]      # [Lp, E]
        q, k, v = self._qkv(x)                              # [Lp, H, D]
        out = attend_prompt(q, k, v)
        y = x + out.reshape(lp, -1) @ self.p['wo']
        cache = write_prompt(cache, 0, slot, k, v)
        logits = self._head(y)                              # [Lp, V]
        return cache, logits[length - 1]

    def decode(self, cache, tokens, positions):
        x = self.p['emb'][tokens] + self.p['pos'][positions]  # [B, E]
        q, k, v = self._qkv(x)                                # [B, H, D]
        cache = write_token(cache, 0, k, v, positions)
        out = attend(cache, 0, q, lengths=positions + 1)
        y = x + out.reshape(x.shape[0], -1) @ self.p['wo']
        return cache, self._head(y)

    # -- paged contract (see paged_kv.py) -------------------------------
    def init_paged_cache(self, num_pages, page_size):
        from . import paged_kv
        return paged_kv.create_paged_cache(
            1, num_pages, page_size, self.num_heads, self.head_dim)

    def prefill_chunk(self, cache, block_row, tokens, start, length):
        from . import paged_kv
        cb = tokens.shape[0]
        pos = jnp.minimum(start + jnp.arange(cb), self.max_seq - 1)
        x = self.p['emb'][tokens] + self.p['pos'][pos]        # [Cb, E]
        q, k, v = self._qkv(x)                                # [Cb, H, D]
        cache = paged_kv.write_chunk(cache, 0, block_row, k, v, start,
                                     length)
        out = paged_kv.attend_chunk(cache, 0, q, block_row, start)
        y = x + out.reshape(cb, -1) @ self.p['wo']
        return cache, self._head(y)                           # [Cb, V]

    def verify_tokens(self, cache, block_tables, tokens, positions):
        from . import paged_kv
        pos = jnp.minimum(positions, self.max_seq - 1)
        x = self.p['emb'][tokens] + self.p['pos'][pos]        # [B, K, E]
        q, k, v = self._qkv(x)                                # [B, K, H, D]
        cache = paged_kv.write_tokens(cache, 0, block_tables, k, v,
                                      positions)
        out = paged_kv.attend_tokens(cache, 0, q, block_tables, positions)
        y = x + out.reshape(out.shape[0], out.shape[1], -1) @ self.p['wo']
        return cache, self._head(y)                           # [B, K, V]

    def reference_decode(self, prompt, max_new_tokens):
        """Greedy decode with NO cache (full forward each step): the
        independent oracle the KV-cache path is verified against."""
        toks = list(np.asarray(prompt, np.int32))
        for _ in range(int(max_new_tokens)):
            x = self.p['emb'][jnp.asarray(toks)] + self.p['pos'][:len(toks)]
            q, k, v = self._qkv(x)
            out = attend_prompt(q, k, v)
            y = x + out.reshape(len(toks), -1) @ self.p['wo']
            nxt = int(np.asarray(jnp.argmax(self._head(y)[-1])))
            toks.append(nxt)
            if self.eos_id is not None and nxt == self.eos_id:
                break
        return toks[len(np.asarray(prompt)):]
