"""Paged KV cache: block-table attention for continuous-batching decode.

The fixed-slot cache (``kv_cache.py``) reserves ``max_seq`` rows per
sequence, so slot count — and therefore serving concurrency — is capped at
``HBM / (L*S*H*D)`` even though most sequences are far shorter than
``max_seq``. The paged cache (vLLM's PagedAttention idea, sized for this
runtime) stores K/V in fixed-size **pages** ``[L, P, page_size, H, D]``
and gives every sequence a **block table**: a fixed-length ``[max_pages]``
row of page indices. Memory is allocated page-by-page as a sequence grows,
so the same HBM sustains several times the concurrency — the only waste is
the tail of the last page.

Everything the compiled path touches is **fixed shape**: the cache array,
the block tables, the gather index they form. Joining, leaving, growing,
prefix sharing — all of it is host-side bookkeeping over the allocator and
the block-table rows; the jitted decode/prefill/verify programs never see
a shape change, so the PR-6 zero-recompile guarantee holds (graftlint
GL017 statically polices the shape-polymorphic alternative: boolean-mask
indexing / ``nonzero()`` in traced code).

Three cooperating pieces:

- **device math** (pure jnp, trace-safe): ``write_chunk`` /
  ``write_tokens`` scatter K/V through a block table;
  ``attend_chunk`` / ``attend_tokens`` gather a sequence's pages back into
  a virtual ``[S, H, D]`` view and run position-masked attention over it.
- **``PageAllocator``** (host): a free-list with refcounts. Page 0 is the
  reserved **null page** — block-table padding and masked writes land
  there, so inactive rows never corrupt live data.
- **``PrefixCache``** (host): hash-consing of *full* pages by
  content-chain digest (the digest of a page commits to every token
  before it, so two sequences share a page only when their entire prefix
  matches — the condition under which their K/V is identical). Shared
  system prompts are prefilled once and refcounted; entries pin their
  page with one cache-owned reference and are evicted LRU-first under
  allocation pressure.
"""
import collections
import hashlib

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['NULL_PAGE', 'PagesExhaustedError', 'PageAllocator', 'PrefixCache',
           'chain_hashes', 'create_paged_cache', 'write_chunk',
           'write_tokens', 'gather_kv', 'attend_chunk', 'attend_tokens']

# Block-table padding and masked (invalid) writes are routed to page 0; it
# is never handed out by the allocator and never read under a live mask.
NULL_PAGE = 0


# ---------------------------------------------------------------------------
# device math (pure jnp — safe under jax.jit)
# ---------------------------------------------------------------------------

def create_paged_cache(num_layers, num_pages, page_size, num_heads, head_dim,
                       dtype=jnp.float32):
    """Zeroed paged cache pytree: ``{'k','v'}`` of ``[L, P, ps, H, D]``."""
    shape = (int(num_layers), int(num_pages), int(page_size),
             int(num_heads), int(head_dim))
    # host-built zeros: device transfer only, no tiny fill-program compile
    # (keeps an AOT cold boot at jax.compiles == 0 — see compilecache)
    z = np.zeros(shape, np.dtype(dtype))
    return {'k': jnp.asarray(z), 'v': jnp.asarray(z)}


def write_chunk(cache, layer, block_row, k, v, start, nvalid):
    """Scatter one sequence's chunk K/V (``[Cb, H, D]``) into its pages.

    Row ``i`` lands at absolute position ``start + i``; rows at or beyond
    ``nvalid`` (bucket padding) are routed to the null page. ``start`` and
    ``nvalid`` may be traced scalars — chunked prefill at any offset is
    the same compiled program.
    """
    ps = cache['k'].shape[2]
    cb = k.shape[0]
    idx = jnp.arange(cb)
    pos = start + idx
    valid = idx < nvalid
    slot = jnp.clip(pos // ps, 0, block_row.shape[0] - 1)
    pages = jnp.where(valid, block_row[slot], NULL_PAGE)
    offs = pos % ps
    return {'k': cache['k'].at[layer, pages, offs].set(k),
            'v': cache['v'].at[layer, pages, offs].set(v)}


def write_tokens(cache, layer, block_tables, k, v, positions):
    """Scatter per-slot K/V (``[B, K, H, D]``) at absolute ``positions``
    (``[B, K]``) through each slot's block-table row. Inactive slots carry
    an all-null block row, so their writes land in the null page."""
    ps = cache['k'].shape[2]
    slot = jnp.clip(positions // ps, 0, block_tables.shape[1] - 1)
    pages = jnp.take_along_axis(block_tables, slot, axis=1)      # [B, K]
    offs = positions % ps
    return {'k': cache['k'].at[layer, pages, offs].set(k),
            'v': cache['v'].at[layer, pages, offs].set(v)}


def gather_kv(cache, layer, block_tables):
    """Gather every slot's pages into virtual ``[B, MP*ps, H, D]`` K/V
    views — the fixed-shape page-index gather the compiled attention
    reads (never a data-dependent boolean mask)."""
    k = cache['k'][layer][block_tables]          # [B, MP, ps, H, D]
    v = cache['v'][layer][block_tables]
    b, mp, ps, h, d = k.shape
    return k.reshape(b, mp * ps, h, d), v.reshape(b, mp * ps, h, d)


def attend_tokens(cache, layer, q, block_tables, positions):
    """Position-masked attention of per-slot queries over paged K/V.

    ``q`` is ``[B, K, H, D]`` (``K`` query tokens per slot — 1 for plain
    decode, ``draft_k+1`` for a speculative verify), ``positions``
    ``[B, K]`` their absolute positions. A query at position ``p`` sees
    keys at positions ``<= p`` (its own K/V is already written), which
    covers both the committed prefix and intra-batch causality in one
    mask. Returns ``[B, K, H, D]``.
    """
    k, v = gather_kv(cache, layer, block_tables)
    d = q.shape[-1]
    scores = jnp.einsum('bkhd,bshd->bkhs', q, k) / jnp.sqrt(float(d))
    s = jnp.arange(k.shape[1])
    mask = s[None, None, None, :] <= positions[:, :, None, None]
    scores = jnp.where(mask, scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum('bkhs,bshd->bkhd', w, v)


def attend_chunk(cache, layer, q, block_row, start):
    """One sequence's chunk attention over its own pages: ``q`` ``[Cb, H,
    D]`` at positions ``start + i``. The ``key_pos <= start + i`` mask
    yields causal attention over cached prefix + intra-chunk in one shot.
    Padded rows produce garbage outputs the caller never reads."""
    k = cache['k'][layer][block_row]             # [MP, ps, H, D]
    v = cache['v'][layer][block_row]
    mp, ps, h, d = k.shape
    k = k.reshape(mp * ps, h, d)
    v = v.reshape(mp * ps, h, d)
    scores = jnp.einsum('ihd,jhd->hij', q, k) / jnp.sqrt(float(d))
    i = start + jnp.arange(q.shape[0])
    mask = jnp.arange(mp * ps)[None, None, :] <= i[None, :, None]
    scores = jnp.where(mask, scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum('hij,jhd->ihd', w, v)


# ---------------------------------------------------------------------------
# host-side bookkeeping
# ---------------------------------------------------------------------------

class PagesExhaustedError(RuntimeError):
    """The page pool is empty: memory, not traffic, is the limit.

    Callers stall/preempt/shed; the doctor's ``kv_page_exhaustion``
    detector names the condition so it is not misdiagnosed as overload.
    """

    def __init__(self, num_pages):
        super().__init__(
            f"paged KV cache: all {num_pages - 1} usable page(s) are "
            "allocated — grow num_pages, shrink page_size tail waste, or "
            "enable prefix_cache for shared prompts")
        self.num_pages = num_pages


class PageAllocator:
    """Free-list page allocator with refcounts (prefix sharing).

    Page 0 is reserved as the null page and never allocated. ``alloc``
    returns a page with refcount 1; ``incref``/``decref`` manage sharing,
    and a page returns to the free list when its count reaches zero.
    """

    def __init__(self, num_pages):
        self.num_pages = int(num_pages)
        if self.num_pages < 2:
            raise ValueError(
                f"PageAllocator: need >= 2 pages (page 0 is the reserved "
                f"null page), got {num_pages}")
        self._free = collections.deque(range(1, self.num_pages))
        self._refs = {}
        self.allocated_total = 0
        self.freed_total = 0

    @property
    def usable(self):
        return self.num_pages - 1

    def free_count(self):
        return len(self._free)

    def used_count(self):
        return self.usable - len(self._free)

    def utilization(self):
        return self.used_count() / self.usable if self.usable else 0.0

    def alloc(self):
        if not self._free:
            raise PagesExhaustedError(self.num_pages)
        page = self._free.popleft()
        self._refs[page] = 1
        self.allocated_total += 1
        return page

    def incref(self, page):
        if page not in self._refs:
            raise ValueError(f"PageAllocator: incref of free page {page}")
        self._refs[page] += 1

    def decref(self, page):
        r = self._refs.get(page)
        if r is None:
            raise ValueError(f"PageAllocator: decref of free page {page}")
        if r == 1:
            del self._refs[page]
            self._free.append(page)
            self.freed_total += 1
        else:
            self._refs[page] = r - 1

    def refcount(self, page):
        return self._refs.get(page, 0)


def chain_hashes(tokens, page_size):
    """Content-chain digests for every FULL page of ``tokens``.

    Digest ``i`` commits to pages ``0..i`` (each digest folds in the
    previous one), so a digest match implies the entire prefix matches —
    the exact condition under which two sequences' K/V for those
    positions is identical and a page may be shared. The trailing partial
    page (if any) gets no digest: it is never shared (decode writes land
    in it).
    """
    toks = np.ascontiguousarray(np.asarray(tokens, np.int32).ravel())
    out = []
    digest = b''
    for i in range(len(toks) // int(page_size)):
        page = toks[i * page_size:(i + 1) * page_size]
        digest = hashlib.sha256(digest + page.tobytes()).digest()
        out.append(digest)
    return out


class PrefixCache:
    """Hash-consed full pages: chain digest -> page id, LRU-evicted.

    Every entry pins its page with one cache-owned allocator reference, so
    a cached prefix survives its original sequence finishing — the next
    request with the same system prompt adopts the pages instead of
    re-prefilling them. Under allocation pressure ``evict_one`` releases
    the least-recently-used entry whose page is pinned *only* by the
    cache (pages other sequences still attend to are never reclaimed).
    """

    def __init__(self, allocator):
        self._alloc = allocator
        self._entries = collections.OrderedDict()    # digest -> page
        self.hits = 0
        self.misses = 0

    def __len__(self):
        return len(self._entries)

    def lookup(self, digest):
        """-> page id (increfed for the caller) or None. Counts hit/miss."""
        page = self._entries.get(digest)
        if page is None:
            self.misses += 1
            return None
        self._entries.move_to_end(digest)
        self._alloc.incref(page)
        self.hits += 1
        return page

    def probe(self, digests):
        """Count how many leading digests are cached — a side-effect-free
        admission-feasibility check (no refs taken, no hit/miss counted)."""
        n = 0
        for d in digests:
            if d not in self._entries:
                break
            n += 1
        return n

    def insert(self, digest, page):
        """Hash-cons ``page`` under ``digest`` (takes one cache-owned
        reference). A digest already consed keeps its existing page."""
        if digest in self._entries:
            return
        self._alloc.incref(page)
        self._entries[digest] = page

    def evict_one(self):
        """Release the LRU entry whose page only the cache still pins.
        Returns True when a page was freed back to the allocator."""
        for digest, page in self._entries.items():
            if self._alloc.refcount(page) == 1:
                del self._entries[digest]
                self._alloc.decref(page)
                return True
        return False

    def hit_rate(self):
        total = self.hits + self.misses
        return self.hits / total if total else 0.0
